"""Serve response streaming tests (reference strategy:
python/ray/serve/tests/test_streaming_response.py + test_generators):
replica generators -> streaming handles -> SSE/chunked HTTP, with
backpressure and mid-stream fault semantics."""

import asyncio
import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps(serve_cluster):
    yield
    leftover = {key.split("#", 1)[0] for key in serve.status()}
    for app in leftover:
        serve.delete(app)


HTTP_PORT = 8457


def _http_stream(path="/", accept=None, port=HTTP_PORT, timeout=60):
    headers = {"Accept": accept} if accept else {}
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers)
    return urllib.request.urlopen(req, timeout=timeout)


# ---------------------------------------------------------------------------
# handle-level streaming
# ---------------------------------------------------------------------------


def test_handle_stream_sync_iteration(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class Gen:
        def __call__(self, n):
            for i in range(n):
                yield {"chunk": i}

    h = serve.run(Gen.bind(), name="hs", proxy=False)
    out = list(h.options(stream=True).remote(7))
    assert out == [{"chunk": i} for i in range(7)]
    serve.delete("hs")


def test_handle_stream_async_iteration_and_async_gen(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class AGen:
        async def __call__(self, n):
            for i in range(n):
                await asyncio.sleep(0.005)
                yield i * 11

    h = serve.run(AGen.bind(), name="ha", proxy=False)

    async def consume():
        out = []
        async for chunk in h.options(stream=True).remote(5):
            out.append(chunk)
        return out

    assert asyncio.run(consume()) == [0, 11, 22, 33, 44]
    serve.delete("ha")


def test_handle_stream_incremental_delivery(serve_cluster):
    """First chunk arrives long before the generator finishes."""

    @serve.deployment(num_cpus=0.1)
    class Slow:
        async def __call__(self, _):
            for i in range(4):
                yield i
                await asyncio.sleep(0.4)

    h = serve.run(Slow.bind(), name="hslow", proxy=False)
    gen = h.options(stream=True).remote(None)
    t0 = time.time()
    first = next(iter(gen))
    first_latency = time.time() - t0
    assert first == 0
    assert first_latency < 1.2, first_latency
    assert list(gen) == [1, 2, 3]
    serve.delete("hslow")


def test_stream_non_generator_method_raises(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class Plain:
        def __call__(self, x):
            return x

    h = serve.run(Plain.bind(), name="hplain", proxy=False)
    gen = h.options(stream=True).remote(1)
    with pytest.raises(Exception, match="generator"):
        next(iter(gen))
    serve.delete("hplain")


def test_non_stream_call_to_generator_raises_helpfully(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class Gen:
        def __call__(self, n):
            yield n

    h = serve.run(Gen.bind(), name="hgen2", proxy=False)
    with pytest.raises(Exception, match="stream=True"):
        h.remote(1).result()
    serve.delete("hgen2")


def test_stream_backpressure_caps_replica_queue(serve_cluster):
    """max_queued_stream_chunks bounds replica-side produced-but-unread
    chunks: a slow consumer pauses a fast generator instead of letting
    it buffer the whole stream."""

    @serve.deployment(num_cpus=0.1, num_replicas=1,
                      max_queued_stream_chunks=4)
    class Counting:
        def __init__(self):
            self.produced = 0

        async def __call__(self, n):
            for i in range(n):
                self.produced += 1
                yield i

        async def produced_count(self):
            return self.produced

    h = serve.run(Counting.bind(), name="bp", proxy=False)
    gen = h.options(stream=True).remote(80)
    it = iter(gen)
    assert next(it) == 0  # one chunk consumed
    time.sleep(1.0)  # fast producer would have drained all 80 by now
    produced = h.options(method_name="produced_count").remote(
        ).result()
    # 1 read + window 4 + one mid-flight.
    assert produced <= 6, f"backpressure did not engage: {produced}"
    assert [next(it) for _ in range(79)] == list(range(1, 80))
    serve.delete("bp")


def test_stream_consumer_drop_stops_replica_generator(serve_cluster):
    """Dropping the response generator cancels the replica-side body
    (router -> core _release_stream -> actor-lane cancel)."""

    @serve.deployment(num_cpus=0.1, max_queued_stream_chunks=8)
    class Infinite:
        def __init__(self):
            self.produced = 0

        async def __call__(self, _):
            while True:
                self.produced += 1
                yield self.produced

        async def produced_count(self):
            return self.produced

    h = serve.run(Infinite.bind(), name="drop", proxy=False)
    gen = h.options(stream=True).remote(None)
    assert next(iter(gen)) == 1
    gen.cancel()
    time.sleep(1.0)
    n1 = h.options(method_name="produced_count").remote().result()
    time.sleep(0.5)
    n2 = h.options(method_name="produced_count").remote().result()
    assert n2 == n1, f"generator kept running after cancel: {n1}->{n2}"
    serve.delete("drop")


def test_streaming_composition_two_stage_pipeline(serve_cluster):
    """A replica consumes ANOTHER deployment's stream inside its own
    generator loop (draft -> refine) without deadlocking its event
    loop: the handle's stream assignment offloads to the executor and
    the chunk iteration is natively async."""

    @serve.deployment(num_cpus=0.1)
    class Draft:
        async def __call__(self, n):
            for i in range(n):
                await asyncio.sleep(0.005)
                yield i

    @serve.deployment(num_cpus=0.1)
    class Refine:
        def __init__(self, draft):
            self.draft = draft

        async def __call__(self, n):
            async for tok in self.draft.options(stream=True).remote(n):
                yield tok * 10

    h = serve.run(Refine.bind(Draft.bind()), name="pipe", proxy=False)
    # Incremental: the first refined chunk must arrive while the draft
    # stage is still producing, proving chunks flow stage-to-stage
    # instead of being buffered per stage.
    gen = h.options(stream=True).remote(40)
    t0 = time.time()
    it = iter(gen)
    assert next(it) == 0
    assert time.time() - t0 < 1.5
    assert list(it) == [i * 10 for i in range(1, 40)]
    serve.delete("pipe")


# ---------------------------------------------------------------------------
# HTTP proxy streaming
# ---------------------------------------------------------------------------


def test_http_sse_first_chunk_before_finish_and_in_order(serve_cluster):
    """Tier-1 e2e: the first SSE chunk of a 100-chunk generator arrives
    before the generator finishes, and chunks arrive in order."""

    @serve.deployment(num_cpus=0.1)
    class Tokens:
        async def __call__(self, request):
            for i in range(100):
                yield {"token": i}
                await asyncio.sleep(0.02)  # whole stream takes ~2s

    serve.run(Tokens.bind(), name="sse", http_port=HTTP_PORT)
    t0 = time.time()
    resp = _http_stream(accept="text/event-stream")
    assert "text/event-stream" in resp.headers.get("Content-Type", "")
    first = resp.readline().decode()
    first_latency = time.time() - t0
    assert first.startswith("data: "), first
    assert json.loads(first[len("data: "):]) == {"token": 0}
    assert first_latency < 1.5, (
        f"first chunk took {first_latency:.2f}s — not streamed")
    tokens = [json.loads(ln[len(b"data: "):].decode())["token"]
              for ln in resp.readlines()
              if ln.startswith(b"data: {")]
    assert tokens == list(range(1, 100))
    serve.delete("sse")


def test_http_chunked_negotiation_and_format_pin(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class Words:
        def __call__(self, request):
            for w in ("alpha", "beta", "gamma"):
                yield w + " "

    serve.run(Words.bind(), name="chunked", http_port=HTTP_PORT)
    # No Accept header -> chunked transfer, raw payloads.
    resp = _http_stream()
    assert "application/octet-stream" in resp.headers.get(
        "Content-Type", "")
    assert resp.read().decode() == "alpha beta gamma "
    serve.delete("chunked")

    # stream_format="sse" pins SSE even without the Accept header.
    @serve.deployment(num_cpus=0.1, stream_format="sse")
    class Pinned:
        def __call__(self, request):
            yield "x"

    serve.run(Pinned.bind(), name="pinned", http_port=HTTP_PORT)
    # The proxy's router refreshes its table on a 1s throttle; right
    # after a redeploy at the same route it may briefly serve the old
    # entry — poll past that window.
    deadline = time.time() + 10
    ctype, body = "", ""
    while time.time() < deadline:
        resp = _http_stream()
        ctype = resp.headers.get("Content-Type", "")
        body = resp.read().decode()
        if "text/event-stream" in ctype:
            break
        time.sleep(0.5)
    assert "text/event-stream" in ctype, ctype
    assert "data: x" in body and "event: end" in body
    serve.delete("pinned")


def test_http_midstream_app_error_terminal_chunk(serve_cluster):
    """A generator raising mid-stream yields a terminal error event to
    the HTTP client instead of a hang or a silent truncation."""

    @serve.deployment(num_cpus=0.1)
    class Exploding:
        def __call__(self, request):
            yield "ok-1"
            yield "ok-2"
            raise ValueError("stream exploded mid-flight")

    serve.run(Exploding.bind(), name="boom", http_port=HTTP_PORT)
    body = _http_stream(accept="text/event-stream").read().decode()
    assert "data: ok-1" in body and "data: ok-2" in body
    assert "event: error" in body, body
    assert "stream exploded mid-flight" in body
    # Chunked framing carries the documented error trailer.
    body2 = _http_stream().read().decode()
    assert "[stream-error]" in body2 and "stream exploded" in body2
    serve.delete("boom")


def test_http_midstream_replica_death_terminal_error(serve_cluster):
    """Tier-1 e2e: killing the replica mid-stream surfaces a terminal
    error event (not a hang), and the router reroutes the next request
    once the controller restores a replica."""

    @serve.deployment(num_cpus=0.1)
    class Endless:
        async def __call__(self, request):
            for i in range(10_000):
                yield {"token": i}
                await asyncio.sleep(0.02)

    serve.run(Endless.bind(), name="kill", http_port=HTTP_PORT)
    resp = _http_stream(accept="text/event-stream", timeout=90)
    assert resp.readline().startswith(b"data: ")  # stream is live

    # Kill the replica mid-stream.
    victims = [a for a in ray_tpu.list_named_actors(True)
               if a["name"].startswith("SERVE_REPLICA::kill#")]
    assert victims, "no replica found to kill"
    ray_tpu.kill(ray_tpu.get_actor(
        victims[0]["name"], victims[0].get("namespace", "")))

    deadline = time.time() + 60
    saw_error = False
    while time.time() < deadline:
        line = resp.readline()
        if not line:
            break
        if line.startswith(b"event: error"):
            saw_error = True
            break
    assert saw_error, "client never saw a terminal error event"

    # The controller replaces the replica; the next request reroutes.
    deadline = time.time() + 90
    rerouted = None
    while time.time() < deadline:
        try:
            r = _http_stream(accept="text/event-stream", timeout=30)
            line = r.readline()
            if line.startswith(b"data: "):
                rerouted = line
                r.close()
                break
        except Exception:
            pass
        time.sleep(1.0)
    assert rerouted is not None, "router never recovered a route"
    serve.delete("kill")


# ---------------------------------------------------------------------------
# gRPC streaming
# ---------------------------------------------------------------------------


def test_grpc_server_streaming_and_unimplemented(serve_cluster):
    import pickle

    grpc = pytest.importorskip("grpc")

    @serve.deployment(num_cpus=0.1)
    class GGen:
        def __call__(self, n):
            for i in range(n):
                yield i * 2

    @serve.deployment(num_cpus=0.1, route_prefix="/plain")
    class GPlain:
        def __call__(self, x):
            return x

    serve.run(GGen.bind(), name="ggen", http_port=HTTP_PORT)
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_grpc_port.remote(), timeout=30)
    assert port
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    call = ch.unary_stream(
        "/ray_tpu.serve.UserDefinedStreamingService/ggen")
    # The gRPC proxy's router refreshes on the same 1s throttle as the
    # HTTP side — poll past any stale-table window from earlier tests.
    chunks, deadline = [], time.time() + 15
    while time.time() < deadline:
        try:
            chunks = [pickle.loads(m)
                      for m in call(pickle.dumps(((4,), {})),
                                    timeout=60)]
            break
        except grpc.RpcError:
            time.sleep(0.5)
    assert chunks == [0, 2, 4, 6]
    serve.delete("ggen")

    # Streaming service on a non-generator deployment: clear error.
    serve.run(GPlain.bind(), name="gplain", route_prefix="/gplain",
              http_port=HTTP_PORT)
    call = ch.unary_stream(
        "/ray_tpu.serve.UserDefinedStreamingService/gplain")
    code, deadline = None, time.time() + 15
    while time.time() < deadline:
        with pytest.raises(grpc.RpcError) as err:
            list(call(pickle.dumps(((1,), {})), timeout=60))
        code = err.value.code()
        if code != grpc.StatusCode.NOT_FOUND:  # stale-table window
            break
        time.sleep(0.5)
    assert code in (grpc.StatusCode.UNIMPLEMENTED,
                    grpc.StatusCode.INTERNAL), code
    ch.close()
    serve.delete("gplain")


# ---------------------------------------------------------------------------
# @serve.batch generator guard
# ---------------------------------------------------------------------------


def test_batch_rejects_generator_function_at_decoration():
    with pytest.raises(TypeError, match="stream"):
        @serve.batch
        def gen_batch(requests):
            yield from requests


def test_batch_rejects_generator_return_at_call_time():
    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.01)
    async def bad(requests):
        return (r for r in requests)  # a generator, not a list

    with pytest.raises(TypeError, match="generator"):
        asyncio.run(bad(1))


# ---------------------------------------------------------------------------
# streaming observability
# ---------------------------------------------------------------------------


def test_stream_metrics_and_flight_events(serve_cluster):
    from ray_tpu.util import flight_recorder, telemetry

    @serve.deployment(num_cpus=0.1)
    class MGen:
        def __call__(self, n):
            for i in range(n):
                yield i

    h = serve.run(MGen.bind(), name="met", proxy=False)
    assert list(h.options(stream=True).remote(5)) == list(range(5))

    # The driver-side router recorded the stream lifecycle (the done
    # callback fires on the owner loop; poll out the tiny race with the
    # consumer's StopIteration).
    deadline = time.time() + 10
    chunk_counts = []
    while time.time() < deadline and not chunk_counts:
        m = telemetry.metric("ray_tpu_serve_stream_chunks_total")
        chunk_counts = [v for tags, v in m._values.items()
                        if ("deployment", "met#MGen") in tags]
        time.sleep(0.05)
    assert chunk_counts and chunk_counts[0] >= 5
    ttft = telemetry.metric("ray_tpu_serve_stream_ttft_seconds")
    assert any(("deployment", "met#MGen") in tags
               for tags in ttft._hists), ttft._hists

    events = [e for e in flight_recorder.snapshot()
              if e["subsystem"] == "serve"
              and e["event"] == "stream_started"
              and (e.get("tags") or {}).get("deployment") == "met#MGen"]
    assert events, "stream_started never recorded"

    # Abort path: a mid-stream app error tags an abort reason.
    @serve.deployment(num_cpus=0.1)
    class MBoom:
        def __call__(self, _):
            yield 1
            raise RuntimeError("abort-metric")

    h2 = serve.run(MBoom.bind(), name="metboom", proxy=False)
    gen = h2.options(stream=True).remote(None)
    with pytest.raises(Exception, match="abort-metric"):
        list(gen)
    deadline = time.time() + 10
    aborted = []
    while time.time() < deadline and not aborted:
        aborts = telemetry.metric("ray_tpu_serve_stream_aborts_total")
        aborted = [tags for tags in aborts._values
                   if ("deployment", "metboom#MBoom") in tags
                   and ("reason", "app_error") in tags]
        time.sleep(0.05)
    assert aborted, "stream abort never counted"
    ev = [e for e in flight_recorder.snapshot()
          if e["subsystem"] == "serve"
          and e["event"] == "stream_aborted"]
    assert ev, "stream_aborted never recorded"
    serve.delete("met")
    serve.delete("metboom")


# ---------------------------------------------------------------------------
# chaos soak (slow lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_replica_killer_midstream_soak(serve_cluster):
    """Slow soak: a ReplicaKiller takes replicas down while clients hold
    open streams; every interrupted client sees a terminal error (never
    a hang) and fresh requests keep being served by rerouted/replaced
    replicas."""
    from ray_tpu.util.chaos import ReplicaKiller

    @serve.deployment(num_cpus=0.1, num_replicas=2)
    class SoakGen:
        async def __call__(self, _):
            for i in range(5_000):
                yield i
                await asyncio.sleep(0.01)

    h = serve.run(SoakGen.bind(), name="soak", proxy=False)
    killer = (ray_tpu.remote(ReplicaKiller)
              .options(name="_chaos_replica_killer", num_cpus=0.1)
              .remote(kill_interval_s=2.0, max_kills=2, app="soak",
                      deployment="SoakGen", seed=7, max_duration_s=45))
    run_ref = killer.run.remote()

    outcomes = {"errors": 0, "finished": 0}
    deadline = time.time() + 60
    while time.time() < deadline:
        gen = h.options(stream=True).remote(None)
        try:
            n = 0
            for _ in gen:
                n += 1
                if n >= 200:
                    gen.cancel()
                    break
            outcomes["finished"] += 1
        except Exception:
            outcomes["errors"] += 1  # terminal error, not a hang
        kills = ray_tpu.get(killer.get_killed.remote(), timeout=10)
        if len(kills) >= 2 and outcomes["errors"] >= 1:
            break
    kills = ray_tpu.get(run_ref, timeout=90)
    assert kills >= 1, "killer never struck"
    assert outcomes["errors"] >= 1, (
        f"no client observed a mid-stream kill: {outcomes}")
    # The deployment still serves after the chaos window.
    deadline = time.time() + 90
    recovered = False
    while time.time() < deadline and not recovered:
        try:
            gen = h.options(stream=True).remote(None)
            next(iter(gen))
            gen.cancel()
            recovered = True
        except Exception:
            time.sleep(1.0)
    assert recovered, "deployment never recovered after chaos"
    ray_tpu.kill(killer)
    serve.delete("soak")
