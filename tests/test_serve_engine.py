"""Continuous-batching engine tests (serve/engine/): iteration-level
admission, per-sequence backpressure/eviction, the TTFT/queue-depth
autoscaling loop, and the proxy's bounded request-body streaming.

Reference strategy: Orca-style iteration-level scheduling asserted
end-to-end — a request arriving mid-decode must see a TTFT bounded by a
few decode iterations, never the residual decode time of the in-flight
batch."""

import asyncio
import http.client
import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

HTTP_PORT = 8459
BODY_LIMIT = 4096


@pytest.fixture(scope="module")
def serve_cluster():
    # The proxy reads serve_max_request_body_bytes in ITS process; env
    # set before init reaches workers through the spawn environment.
    os.environ["RAY_TPU_SERVE_MAX_REQUEST_BODY_BYTES"] = str(BODY_LIMIT)
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield
    serve.shutdown()
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_SERVE_MAX_REQUEST_BODY_BYTES", None)


@pytest.fixture(autouse=True)
def _cleanup_apps(serve_cluster):
    yield
    leftover = {key.split("#", 1)[0] for key in serve.status()}
    for app in leftover:
        serve.delete(app)


# ---------------------------------------------------------------------------
# engine basics: auto-wrap + contract modes
# ---------------------------------------------------------------------------


def test_engine_auto_wrap_stream_basic(serve_cluster):
    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=4))
    class Tok:
        async def __call__(self, n):
            for i in range(n):
                await asyncio.sleep(0.005)
                yield {"t": i}

    h = serve.run(Tok.bind(), name="eb", proxy=False)
    out = list(h.options(stream=True).remote(7))
    assert out == [{"t": i} for i in range(7)]
    serve.delete("eb")


def test_engine_sync_generator_auto_wrap(serve_cluster):
    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=3))
    def doubles(n):
        for i in range(n):
            yield i * 2

    h = serve.run(doubles.bind(), name="esync", proxy=False)
    assert list(h.options(stream=True).remote(4)) == [0, 2, 4, 6]
    serve.delete("esync")


def test_engine_contract_prefill_decode_evict(serve_cluster):
    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=4))
    class Contract:
        """KV-cache-shaped contract: batch_state maps seq_id to
        (remaining, next). ``evict`` frees slots and records it was
        called — the engine must invoke it for finished sequences."""

        def __init__(self):
            self.evicted = []

        def prefill(self, state, requests):
            state = state or {}
            for r in requests:
                state[r.seq_id] = [r.args[0], 0]
            return state

        def decode_step(self, state):
            out = {}
            for sid, (n, i) in list(state.items()):
                if i >= n:
                    out[sid] = serve.Finished()
                else:
                    out[sid] = {"tok": i}
                    state[sid][1] += 1
            return out

        def evict(self, state, seq_ids):
            self.evicted.extend(seq_ids)
            for sid in seq_ids:
                (state or {}).pop(sid, None)
            return state

        def evicted_count(self):
            return len(self.evicted)

    h = serve.run(Contract.bind(), name="ec", proxy=False)
    g1 = h.options(stream=True).remote(5)
    g2 = h.options(stream=True).remote(3)
    assert list(g1) == [{"tok": i} for i in range(5)]
    assert list(g2) == [{"tok": i} for i in range(3)]
    deadline = time.time() + 10
    n = 0
    while time.time() < deadline:
        n = h.options(method_name="evicted_count").remote().result()
        if n >= 2:
            break
        time.sleep(0.1)
    assert n >= 2, "evict hook never called for finished sequences"
    serve.delete("ec")


def test_engine_unary_call_raises_helpfully(serve_cluster):
    @serve.deployment(num_cpus=0.1, engine=serve.EngineConfig())
    class Gen:
        async def __call__(self, n):
            yield n

    h = serve.run(Gen.bind(), name="eun", proxy=False)
    with pytest.raises(Exception, match="stream=True"):
        h.remote(1).result()
    serve.delete("eun")


# ---------------------------------------------------------------------------
# iteration-level admission (the acceptance bar)
# ---------------------------------------------------------------------------


def test_mid_decode_admission_bounds_ttft(serve_cluster):
    """A request arriving while the batch is mid-decode is admitted
    between iterations: its TTFT is a few decode iterations (~50ms
    each), NOT the first request's multi-second residual decode."""

    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=4))
    class Slow:
        async def __call__(self, n):
            for i in range(n):
                await asyncio.sleep(0.05)
                yield i

    h = serve.run(Slow.bind(), name="emid", proxy=False)
    # Request A: ~5s of residual decode after its first chunk.
    gen_a = h.options(stream=True).remote(100)
    it_a = iter(gen_a)
    assert next(it_a) == 0  # A is decoding now
    # Request B arrives mid-decode.
    t0 = time.time()
    gen_b = h.options(stream=True).remote(3)
    first_b = next(iter(gen_b))
    ttft_b = time.time() - t0
    assert first_b == 0
    # Bound: a handful of iterations + routing overhead — far below
    # A's ~5s residual. (A flush-window batcher would be >= residual.)
    assert ttft_b < 1.5, (
        f"mid-decode TTFT {ttft_b:.2f}s — request waited for the "
        "in-flight batch instead of joining it")
    assert list(gen_b) == [1, 2]
    gen_a.cancel()
    serve.delete("emid")


def test_stalled_sequence_evicted_batch_keeps_decoding(serve_cluster):
    """decode_iteration_timeout_s: one async generator awaiting a hung
    upstream is failed terminally; the rest of the batch (and new
    admissions) keep flowing instead of the whole engine wedging."""

    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(
                          max_batch_size=4,
                          decode_iteration_timeout_s=0.5))
    class Stally:
        async def __call__(self, hang):
            yield "first"
            if hang:
                await asyncio.sleep(3600)  # hung upstream
            yield "second"

    h = serve.run(Stally.bind(), name="estall", proxy=False)
    gen_hung = h.options(stream=True).remote(True)
    it_hung = iter(gen_hung)
    assert next(it_hung) == "first"  # hung seq is now mid-await
    # A healthy request admitted alongside the stalled one completes.
    t0 = time.time()
    assert list(h.options(stream=True).remote(False)) == [
        "first", "second"]
    assert time.time() - t0 < 2.0, "healthy sequence was wedged"
    # The stalled sequence fails terminally — never hangs its consumer.
    with pytest.raises(Exception) as ei:
        for _ in it_hung:
            pass
    assert "decode_iteration_timeout_s" in str(ei.value)
    serve.delete("estall")


# ---------------------------------------------------------------------------
# per-sequence backpressure + eviction
# ---------------------------------------------------------------------------


def test_per_sequence_backpressure_pauses_one_not_all(serve_cluster):
    """A slow consumer's sequence pauses at its credit window while the
    rest of the batch keeps decoding."""

    @serve.deployment(num_cpus=0.1, max_queued_stream_chunks=2,
                      engine=serve.EngineConfig(
                          max_batch_size=4,
                          max_buffered_chunks_per_seq=4))
    class Inf:
        def __init__(self):
            self.counts = {}

        async def __call__(self, tag):
            i = 0
            while True:
                self.counts[tag] = i
                yield i
                i += 1

        async def produced(self, tag):
            return self.counts.get(tag, -1)

    h = serve.run(Inf.bind(), name="ebp", proxy=False)
    gen_a = h.options(stream=True).remote("a")
    it_a = iter(gen_a)
    assert next(it_a) == 0  # a admitted; consumer now stalls
    gen_b = h.options(stream=True).remote("b")
    it_b = iter(gen_b)
    for expect in range(150):
        assert next(it_b) == expect
    a_count = h.options(method_name="produced").remote("a").result()
    b_count = h.options(method_name="produced").remote("b").result()
    assert b_count >= 149
    # a's emission: 1 consumed + engine window (4) + core stream
    # window (2) + in-flight slack — far below b's 150.
    assert a_count <= 12, (
        f"paused sequence kept decoding: a={a_count} b={b_count}")
    # Draining a resumes it mid-batch.
    assert next(it_a) == 1
    gen_a.cancel()
    gen_b.cancel()
    serve.delete("ebp")


def test_cancel_evicts_sequence_mid_batch(serve_cluster):
    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=4))
    class Inf:
        def __init__(self):
            self.counts = {}

        async def __call__(self, tag):
            i = 0
            while True:
                self.counts[tag] = i
                yield i
                i += 1

        async def produced(self, tag):
            return self.counts.get(tag, -1)

    h = serve.run(Inf.bind(), name="ecan", proxy=False)
    gen_a = h.options(stream=True).remote("a")
    gen_b = h.options(stream=True).remote("b")
    it_a, it_b = iter(gen_a), iter(gen_b)
    assert next(it_a) == 0 and next(it_b) == 0
    gen_a.cancel()
    # The cancelled sequence is evicted from the running batch: its
    # generator stops advancing while b keeps streaming.
    deadline = time.time() + 10
    stalled = None
    while time.time() < deadline:
        n1 = h.options(method_name="produced").remote("a").result()
        time.sleep(0.4)
        n2 = h.options(method_name="produced").remote("a").result()
        if n1 == n2:
            stalled = n1
            break
    assert stalled is not None, "cancelled sequence kept decoding"
    for expect in range(1, 50):
        assert next(it_b) == expect
    gen_b.cancel()
    serve.delete("ecan")


def test_engine_sheds_honestly_when_queue_full(serve_cluster):
    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=1,
                                                max_queued=1))
    class OneAtATime:
        async def __call__(self, _):
            while True:
                await asyncio.sleep(0.02)
                yield 1

    h = serve.run(OneAtATime.bind(), name="eshed", proxy=False)
    gen_a = h.options(stream=True).remote(None)
    assert next(iter(gen_a)) == 1  # a occupies the batch
    gen_b = h.options(stream=True).remote(None)  # parks in the queue
    time.sleep(0.5)
    gen_c = h.options(stream=True).remote(None)  # over max_queued
    with pytest.raises(Exception, match="admission queue full"):
        next(iter(gen_c))
    gen_a.cancel()
    gen_b.cancel()
    serve.delete("eshed")


def test_engine_events_and_metrics_recorded(serve_cluster):
    """engine/admitted + engine/evicted land in replica flight rings
    (visible cluster-wide through the debug plane) and the queue-wait
    histogram is in the driver-collectable metric plane."""
    from ray_tpu.util import debug as udebug

    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=2))
    class Tok:
        async def __call__(self, n):
            for i in range(n):
                yield i

    h = serve.run(Tok.bind(), name="eev", proxy=False)
    assert list(h.options(stream=True).remote(3)) == [0, 1, 2]
    deadline = time.time() + 15
    admitted = evicted = []
    while time.time() < deadline:
        dump = udebug.cluster_debug_dump(include_stacks=False)
        events = [e for entry in dump.get("entries", [])
                  for e in (entry.get("events") or [])
                  if e.get("subsystem") == "engine"
                  and (e.get("tags") or {}).get("deployment")
                  == "eev#Tok"]
        admitted = [e for e in events if e["event"] == "admitted"]
        evicted = [e for e in events if e["event"] == "evicted"]
        if admitted and evicted:
            break
        time.sleep(0.5)
    assert admitted, "engine/admitted never recorded"
    assert evicted, "engine/evicted never recorded"
    serve.delete("eev")


# ---------------------------------------------------------------------------
# the autoscaling loop (acceptance: closed end-to-end in a fake cluster)
# ---------------------------------------------------------------------------


def test_autoscaling_breach_up_idle_down_with_peer_weights(serve_cluster):
    """Sustained TTFT/queue-depth breach scales the engine deployment
    up (the new replica cold-starts published weights through the
    device object plane — fetch-from-peer path), idle occupancy scales
    back down to min_replicas; both decisions are observable via the
    serve/autoscale flight events and the decisions counter."""
    import threading

    import jax.numpy as jnp

    from ray_tpu.util import debug as udebug
    from ray_tpu.util import metrics as um

    serve.publish_weights(
        "cb_weights", {"w": jnp.arange(4096, dtype=jnp.float32)})

    @serve.deployment(
        num_cpus=0.1,
        engine=serve.EngineConfig(max_batch_size=2, max_queued=64),
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3,
            target_ongoing_requests=10_000,  # isolate the new signals
            target_ttft_s=0.2, target_queue_depth=1.0,
            upscale_delay_s=0.5, downscale_delay_s=1.0,
            downscale_occupancy=0.15),
    )
    class Model:
        def __init__(self):
            # Cold start rides the device object plane: the driver and
            # every earlier replica are registered holders, so a
            # scale-up replica pulls shards from a peer.
            self.w = serve.fetch_weights("cb_weights")

        async def __call__(self, n):
            total = float(self.w["w"][0])
            for i in range(n):
                await asyncio.sleep(0.05)
                yield {"t": i, "w0": total}

    h = serve.run(Model.bind(), name="ecb", proxy=False)
    assert serve.status()["ecb#Model"]["target_replicas"] == 1

    stop_at = time.time() + 25

    def drive():
        while time.time() < stop_at:
            try:
                for _ in h.options(stream=True).remote(20):
                    pass
            except Exception:
                time.sleep(0.2)  # shed under overload: keep driving

    threads = [threading.Thread(target=drive) for _ in range(10)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 45
        scaled = ready = 0
        while time.time() < deadline:
            st = serve.status()["ecb#Model"]
            scaled = max(scaled, st["target_replicas"])
            ready = max(ready, st["running_replicas"])
            if scaled >= 2 and ready >= 2:
                break
            time.sleep(0.5)
        assert scaled >= 2, "breach never scaled the deployment up"
        # The scaled-up replica became READY: its __init__ fetched the
        # published weights from a peer holder and passed health.
        assert ready >= 2, "scale-up replica never cold-started"
    finally:
        for t in threads:
            t.join()

    # Idle: occupancy 0 + empty queue -> back down to min_replicas.
    deadline = time.time() + 45
    down = False
    while time.time() < deadline:
        if serve.status()["ecb#Model"]["target_replicas"] == 1:
            down = True
            break
        time.sleep(0.5)
    assert down, "idle engine never scaled down to min_replicas"

    # Observability: decisions counter (cluster metric plane) ...
    deadline = time.time() + 20
    ups, downs = [], []
    while time.time() < deadline and not (ups and downs):
        m = um.collect_metrics().get(
            "ray_tpu_serve_autoscale_decisions_total")
        values = (m or {}).get("values", {})
        ups = [v for tags, v in values.items()
               if dict(tags).get("deployment") == "ecb#Model"
               and dict(tags).get("direction") == "up"]
        downs = [v for tags, v in values.items()
                 if dict(tags).get("deployment") == "ecb#Model"
                 and dict(tags).get("direction") == "down"]
        time.sleep(1.0)
    assert ups, "no up decision counted"
    assert downs, "no down decision counted"
    # ... and serve/autoscale flight events with direction+reason.
    dump = udebug.cluster_debug_dump(include_stacks=False)
    events = [e for entry in dump.get("entries", [])
              for e in (entry.get("events") or [])
              if e.get("subsystem") == "serve"
              and e.get("event") == "autoscale"
              and (e.get("tags") or {}).get("deployment") == "ecb#Model"]
    directions = {(e["tags"].get("direction"), e["tags"].get("reason"))
                  for e in events}
    assert any(d == "up" and r in ("ttft", "queue_depth")
               for d, r in directions), directions
    assert any(d == "down" and r == "idle"
               for d, r in directions), directions
    serve.delete("ecb")
    serve.unpublish("cb_weights")


# ---------------------------------------------------------------------------
# engine through the HTTP proxy + request-body streaming
# ---------------------------------------------------------------------------


def test_engine_http_sse_stream(serve_cluster):
    @serve.deployment(num_cpus=0.1,
                      engine=serve.EngineConfig(max_batch_size=8))
    class Tok:
        async def __call__(self, request):
            for i in range(10):
                await asyncio.sleep(0.005)
                yield {"t": i}

    serve.run(Tok.bind(), name="ehttp", http_port=HTTP_PORT)
    req = urllib.request.Request(
        f"http://127.0.0.1:{HTTP_PORT}/",
        headers={"Accept": "text/event-stream"})
    resp = urllib.request.urlopen(req, timeout=60)
    assert "text/event-stream" in resp.headers.get("Content-Type", "")
    toks = [json.loads(ln[6:])["t"] for ln in resp.readlines()
            if ln.startswith(b"data: {")]
    assert toks == list(range(10))
    serve.delete("ehttp")


def test_http_request_body_streamed_and_bounded_413(serve_cluster):
    """Chunked/streamed request bodies accumulate incrementally under
    serve_max_request_body_bytes; crossing the bound is an honest 413
    (for both declared and chunked-transfer uploads)."""

    @serve.deployment(num_cpus=0.1)
    class EchoLen:
        def __call__(self, request):
            return {"len": len(request.body())}

    serve.run(EchoLen.bind(), name="ebody", http_port=HTTP_PORT)
    deadline = time.time() + 15
    status = None
    while time.time() < deadline:
        c = http.client.HTTPConnection("127.0.0.1", HTTP_PORT,
                                       timeout=30)
        c.request("POST", "/", body=b"x" * 128)
        r = c.getresponse()
        status, body = r.status, r.read()
        if status == 200 and b"128" in body:
            break
        time.sleep(0.5)  # router table refresh window after redeploys
    assert status == 200, status

    # Chunked upload with no Content-Length: the proxy must stop at the
    # bound while accumulating, not after buffering everything.
    def chunks():
        for _ in range(4 * BODY_LIMIT // 512):
            yield b"y" * 512

    c = http.client.HTTPConnection("127.0.0.1", HTTP_PORT, timeout=30)
    try:
        c.request("POST", "/", body=chunks(), encode_chunked=True)
        resp = c.getresponse()
        assert resp.status == 413, resp.status
        assert b"serve_max_request_body_bytes" in resp.read()
    except (BrokenPipeError, ConnectionResetError):
        pass  # server answered 413 and cut the upload mid-stream

    # Declared oversized body: rejected up front from Content-Length.
    c2 = http.client.HTTPConnection("127.0.0.1", HTTP_PORT, timeout=30)
    c2.request("POST", "/", body=b"z" * (BODY_LIMIT * 2))
    assert c2.getresponse().status == 413
    serve.delete("ebody")


def test_sync_contract_hook_timeout_stops_engine_not_races():
    """A SYNC decode_step blocking past decode_iteration_timeout_s
    leaves its executor thread running user code; the engine must stop
    terminally (failed=True, all streams errored, submits fail fast)
    rather than issue a second user call that would race the abandoned
    thread over the same batch state."""
    from ray_tpu.serve.engine import EngineConfig
    from ray_tpu.serve.engine.core import ContinuousBatchingEngine

    calls = []

    class Model:
        def prefill(self, state, reqs):
            return {"ids": [r.seq_id for r in reqs]}

        def decode_step(self, state):
            calls.append(time.time())
            time.sleep(0.8)  # blocks well past the timeout below
            return {}

    async def main():
        eng = ContinuousBatchingEngine(
            Model(), EngineConfig(max_batch_size=2,
                                  decode_iteration_timeout_s=0.1),
            "wedge")
        seq = eng.submit((), {})
        with pytest.raises(RuntimeError, match="executor thread"):
            async for _ in eng.stream(seq):
                pass
        assert eng.failed
        with pytest.raises(RuntimeError, match="shut down|failed"):
            eng.submit((), {})
        # The poisoned call was issued exactly once — never a second
        # user call concurrent with the abandoned thread.
        assert len(calls) == 1, calls

    asyncio.run(main())
    assert len(calls) == 1, calls


# ---------------------------------------------------------------------------
# chaos soak (slow lane): the serve-cb bench shape under ReplicaKiller
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_cb_replica_killer_soak(serve_cluster):
    """ReplicaKiller takes engine replicas down while HTTP clients hold
    open continuous-batched streams: every interrupted client sees a
    terminal error (never a hang — every read is under a deadline), and
    the deployment recovers and re-routes."""
    import threading

    from ray_tpu.util.chaos import ReplicaKiller

    @serve.deployment(num_cpus=0.1, num_replicas=2,
                      engine=serve.EngineConfig(max_batch_size=16,
                                                max_queued=256))
    class SoakTok:
        async def __call__(self, request):
            for i in range(2_000):
                await asyncio.sleep(0.01)
                yield {"t": i}

    serve.run(SoakTok.bind(), name="esoak", http_port=HTTP_PORT)
    killer = (ray_tpu.remote(ReplicaKiller)
              .options(name="_chaos_engine_killer", num_cpus=0.1)
              .remote(kill_interval_s=3.0, max_kills=2, app="esoak",
                      deployment="SoakTok", seed=11, max_duration_s=60))
    run_ref = killer.run.remote()

    outcomes = {"finished": 0, "errors": 0}
    lock = threading.Lock()

    def client():
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{HTTP_PORT}/",
                headers={"Accept": "text/event-stream"})
            resp = urllib.request.urlopen(req, timeout=30)
            n = 0
            while n < 400:
                line = resp.readline()
                if not line:
                    break
                if line.startswith(b"event: error"):
                    raise RuntimeError("terminal stream error")
                if line.startswith(b"data: {"):
                    n += 1
            with lock:
                outcomes["finished"] += 1
        except Exception:
            with lock:
                outcomes["errors"] += 1

    deadline = time.time() + 75
    while time.time() < deadline:
        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), (
            "a stream client hung past every deadline")
        kills = ray_tpu.get(killer.get_killed.remote(), timeout=10)
        if len(kills) >= 2 and outcomes["errors"] >= 1:
            break
    kills = ray_tpu.get(run_ref, timeout=90)
    assert kills >= 1, "killer never struck"
    assert outcomes["errors"] >= 1, (
        f"no client observed a mid-stream kill: {outcomes}")

    # Recovery: replaced replicas serve fresh continuous-batched streams.
    deadline = time.time() + 90
    recovered = False
    while time.time() < deadline and not recovered:
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{HTTP_PORT}/",
                headers={"Accept": "text/event-stream"})
            resp = urllib.request.urlopen(req, timeout=20)
            line = resp.readline()
            if line.startswith(b"data: {"):
                recovered = True
                resp.close()
                break
        except Exception:
            pass
        time.sleep(1.0)
    assert recovered, "deployment never recovered after chaos"
    ray_tpu.kill(killer)
    serve.delete("esoak")
