"""Multi-host clustering: node agents with per-host object stores and the
cross-node object transfer plane.

The substrate runs a node-agent subprocess on the same machine with its
OWN shm arena (distinct namespace), which exercises the full cross-node
protocol — directory lookup, chunked network pull, borrowed-copy ingest —
without a second machine (reference analog:
src/ray/object_manager/pull_manager.h + push_manager.h semantics)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def two_host_cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0, resources={"hostA": 2})
    from ray_tpu import api

    head_port = api._global_node.port
    env = dict(os.environ)
    # The agent must build its own arena/session; make sure nothing from
    # the driver leaks through (it would defeat store isolation).
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--head-host", "127.0.0.1", "--head-port", str(head_port),
         "--num-cpus", "2", "--resources", '{"hostB": 2}',
         "--object-store-memory", str(256 << 20)],
        env=env,
    )
    # Wait for the node to join.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if any(r.get("hostB") for r in [ray_tpu.cluster_resources()]):
            break
        if agent.poll() is not None:
            raise RuntimeError("node agent exited during startup")
        time.sleep(0.2)
    else:
        raise TimeoutError("node agent never joined the cluster")
    yield agent
    agent.terminate()
    agent.wait(timeout=30)
    ray_tpu.shutdown()


BIG = 300_000  # floats; > max_direct_call_object_size -> node store


def test_cluster_spans_two_hosts(two_host_cluster):
    res = ray_tpu.cluster_resources()
    assert res.get("hostA") == 2
    assert res.get("hostB") == 2
    assert res.get("CPU") == 4


def test_driver_pulls_object_created_on_remote_node(two_host_cluster):
    @ray_tpu.remote(resources={"hostB": 1})
    def produce():
        return np.arange(BIG, dtype=np.float64)

    ref = produce.remote()
    out = ray_tpu.get(ref, timeout=120)
    assert out.shape == (BIG,)
    assert float(out[12345]) == 12345.0


def test_remote_worker_pulls_driver_object(two_host_cluster):
    big = np.ones(BIG, dtype=np.float64) * 3.0
    ref = ray_tpu.put(big)

    @ray_tpu.remote(resources={"hostB": 1})
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=120) == float(big.sum())


def test_remote_to_remote_roundtrip(two_host_cluster):
    """B produces, A consumes, then the reverse — locations accumulate."""

    @ray_tpu.remote(resources={"hostB": 1})
    def produce_b():
        return np.full(BIG, 7.0)

    @ray_tpu.remote(resources={"hostA": 1})
    def consume_a(x):
        return float(x[0])

    ref = produce_b.remote()
    assert ray_tpu.get(consume_a.remote(ref), timeout=120) == 7.0
    # Second consumer on A: the pulled copy is already local to A's store.
    assert ray_tpu.get(consume_a.remote(ref), timeout=120) == 7.0


def test_actor_on_remote_node(two_host_cluster):
    @ray_tpu.remote(resources={"hostB": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.add.remote(5), timeout=120) == 5
    assert ray_tpu.get(c.add.remote(2), timeout=120) == 7
    ray_tpu.kill(c)


def test_two_host_trainer_gang(two_host_cluster):
    """A JaxTrainer gang spread across both hosts (one worker each)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_loop(config):
        import jax
        import jax.numpy as jnp

        from ray_tpu.train import session

        ctx = session.get_context()
        x = jnp.ones((8, 4))
        w = jnp.full((4, 2), float(ctx.world_rank + 1))
        loss = float(jnp.sum(x @ w))
        session.report({"loss": loss, "rank": ctx.world_rank,
                        "world": ctx.world_size,
                        "ndev": len(jax.devices())})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1},
            placement_strategy="STRICT_SPREAD", use_tpu=False),
        run_config=RunConfig(name="mh-gang"),
    )
    result = trainer.fit()
    assert result.metrics["world"] == 2


def test_remote_worker_logs_stream_to_driver(two_host_cluster, capfd):
    """print() in a task on the OTHER host shows up on the driver's
    console with a worker prefix (reference: log_monitor.py:103)."""
    @ray_tpu.remote(resources={"hostB": 1})
    def shout():
        print("MULTIHOST-LOG-MARKER hello")
        return 1

    assert ray_tpu.get(shout.remote(), timeout=120) == 1
    deadline = time.monotonic() + 15
    seen = ""
    while time.monotonic() < deadline:
        seen += capfd.readouterr().out
        if "MULTIHOST-LOG-MARKER" in seen:
            break
        time.sleep(0.3)
    assert "MULTIHOST-LOG-MARKER" in seen
    assert "worker=" in seen
