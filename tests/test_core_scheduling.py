"""Scheduler, resources, placement groups (reference model:
python/ray/tests/test_placement_group*.py, test_scheduling*.py)."""

import pytest

import ray_tpu
from ray_tpu.core.ids import NodeID, PlacementGroupID
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.scheduler import Node


def test_resource_set_ops():
    a = ResourceSet({"CPU": 2, "TPU": 1})
    b = ResourceSet({"CPU": 0.5})
    assert (a - b).get("CPU") == 1.5
    assert (a + b).get("CPU") == 2.5
    assert b.is_subset_of(a)
    assert not a.is_subset_of(b)


def test_resource_fixed_point():
    a = ResourceSet({"CPU": 0.1})
    total = ResourceSet()
    for _ in range(10):
        total = total + a
    assert total.get("CPU") == 1.0  # no float drift


def test_node_resources_acquire_release():
    nr = NodeResources(ResourceSet({"CPU": 4}))
    req = ResourceSet({"CPU": 3})
    assert nr.acquire(req)
    assert not nr.acquire(req)
    nr.release(req)
    assert nr.acquire(req)


def test_infeasible_task_fails(ray_start):
    @ray_tpu.remote(num_cpus=128)
    def impossible():
        return 1

    with pytest.raises(Exception):
        ray_tpu.get(impossible.remote(), timeout=60)


def test_fractional_cpus(ray_start):
    @ray_tpu.remote(num_cpus=0.5)
    def half():
        return "ok"

    refs = [half.remote() for _ in range(8)]
    assert ray_tpu.get(refs, timeout=60) == ["ok"] * 8


def test_custom_resources_infeasible(ray_start):
    # The cluster has no "widget" resource.
    @ray_tpu.remote(resources={"widget": 1})
    def needs_widget():
        return 1

    with pytest.raises(Exception):
        ray_tpu.get(needs_widget.remote(), timeout=60)


def test_placement_group_create_ready(ray_start):
    pg = ray_tpu.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=10)
    specs = pg.bundle_specs
    assert len(specs) == 2
    ray_tpu.remove_placement_group(pg)


def test_placement_group_scheduling(ray_start):
    from ray_tpu.core.task_spec import PlacementGroupSchedulingStrategy

    pg = ray_tpu.placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.ready(timeout=10)

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=PlacementGroupSchedulingStrategy(
        placement_group_id_hex=pg.id_hex, bundle_index=0))
    def inside():
        return "placed"

    assert ray_tpu.get(inside.remote(), timeout=60) == "placed"
    ray_tpu.remove_placement_group(pg)


def test_placement_group_infeasible(ray_start):
    pg = ray_tpu.placement_group([{"CPU": 1000}])
    assert not pg.ready(timeout=0.5)
    ray_tpu.remove_placement_group(pg)


def test_bundle_reservation_isolated():
    """Unit test of bundle placement logic on a fake 2-node cluster."""
    from ray_tpu.core.scheduler import ClusterScheduler

    sched = ClusterScheduler(pool=None)
    n1 = Node(NodeID.from_random(), ResourceSet({"CPU": 4}))
    n2 = Node(NodeID.from_random(), ResourceSet({"CPU": 4}))
    sched.add_node(n1)
    sched.add_node(n2)

    pg = PlacementGroupID.from_random()
    ok = sched.try_place_bundles(
        pg, [ResourceSet({"CPU": 3}), ResourceSet({"CPU": 3})], "STRICT_SPREAD"
    )
    assert ok
    states = sched.pg_bundles[pg]
    assert states[0].node_id != states[1].node_id
    assert n1.resources.available.get("CPU") == 1.0

    # Full cluster: a second 2×3-CPU strict-spread PG cannot fit.
    pg2 = PlacementGroupID.from_random()
    assert not sched.try_place_bundles(
        pg2, [ResourceSet({"CPU": 3}), ResourceSet({"CPU": 3})],
        "STRICT_SPREAD",
    )
    sched.remove_pg(pg)
    assert n1.resources.available.get("CPU") == 4.0


def test_strict_pack_one_node():
    from ray_tpu.core.scheduler import ClusterScheduler

    sched = ClusterScheduler(pool=None)
    n1 = Node(NodeID.from_random(), ResourceSet({"CPU": 8}))
    sched.add_node(n1)
    pg = PlacementGroupID.from_random()
    assert sched.try_place_bundles(
        pg, [ResourceSet({"CPU": 4}), ResourceSet({"CPU": 4})], "STRICT_PACK"
    )
    states = sched.pg_bundles[pg]
    assert states[0].node_id == states[1].node_id


def test_tpu_resource_detection():
    from ray_tpu.core.accelerators import TPUAcceleratorManager

    # On the CPU test mesh there are no TPU chips.
    n = TPUAcceleratorManager.detect_num_chips()
    assert n >= 0
    with pytest.raises(ValueError):
        TPUAcceleratorManager.validate_chip_request(3)
    TPUAcceleratorManager.validate_chip_request(4)
