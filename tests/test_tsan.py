"""ThreadSanitizer gate for the native arena (reference: bazel
--config=tsan on the C++ core). Compile+run costs ~1 min, so the
stress itself only runs when RAY_TPU_TSAN=1 (CI race-hunt lane); the
script is also directly runnable: bash cpp/tpustore/tsan_check.sh.

The committed artifact (TSAN_r<NN>.json) is schema-checked in tier-1
so a stale or hand-mangled JSON can't green the lane silently."""

import glob
import json
import os
import re
import subprocess

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every key the artifact must carry, with a validity predicate.
_ARTIFACT_SCHEMA = {
    "lane": lambda v: isinstance(v, str) and "tsan_check.sh" in v,
    "stress": lambda v: isinstance(v, str) and "fsanitize=thread" in v,
    "result": lambda v: v == "OK",
    "races_found": lambda v: v == 0,
    "run_date": lambda v: isinstance(v, str)
    and re.fullmatch(r"\d{4}-\d{2}-\d{2}", v) is not None,
}


def _latest_artifact() -> str:
    paths = sorted(glob.glob(os.path.join(_REPO, "TSAN_r*.json")))
    assert paths, "no TSAN_r*.json artifact committed"
    return paths[-1]


def test_tsan_artifact_schema():
    """Tier-1: the newest committed TSan artifact parses and proves a
    clean run — every schema key present and valid."""
    path = _latest_artifact()
    with open(path) as f:
        data = json.load(f)
    for key, ok in _ARTIFACT_SCHEMA.items():
        assert key in data, f"{os.path.basename(path)} missing {key!r}"
        assert ok(data[key]), (
            f"{os.path.basename(path)}: bad {key!r}: {data[key]!r}")
    extra = set(data) - set(_ARTIFACT_SCHEMA)
    assert not extra, f"unknown artifact keys (update the schema): {extra}"


@pytest.mark.skipif(os.environ.get("RAY_TPU_TSAN") != "1",
                    reason="set RAY_TPU_TSAN=1 to run the TSan stress")
def test_native_store_under_tsan():
    out = subprocess.run(
        ["bash", os.path.join(_REPO, "cpp", "tpustore", "tsan_check.sh")],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "OK" in out.stdout
