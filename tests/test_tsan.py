"""ThreadSanitizer gate for the native arena (reference: bazel
--config=tsan on the C++ core). Compile+run costs ~1 min, so it only
runs when RAY_TPU_TSAN=1 (CI race-hunt lane); the script is also
directly runnable: bash cpp/tpustore/tsan_check.sh."""

import os
import subprocess

import pytest


@pytest.mark.skipif(os.environ.get("RAY_TPU_TSAN") != "1",
                    reason="set RAY_TPU_TSAN=1 to run the TSan stress")
def test_native_store_under_tsan():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        ["bash", os.path.join(repo, "cpp", "tpustore", "tsan_check.sh")],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "OK" in out.stdout
