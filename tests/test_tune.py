"""Tests for ray_tpu.tune (reference strategy: python/ray/tune/tests/
test_tune_restore.py, test_trial_scheduler.py, test_basic_variant.py)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.schedulers import (
    CONTINUE,
    STOP,
    AsyncHyperBandScheduler,
    PopulationBasedTraining,
    ExploitDirective,
)


@pytest.fixture(scope="module")
def tune_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


# -- search spaces (no cluster needed) --------------------------------------


def test_basic_variant_grid_and_samples():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0, 1),
        "layers": tune.randint(1, 4),
    }
    gen = BasicVariantGenerator(space, num_samples=3, seed=0)
    configs = gen.next_configs()
    assert len(configs) == 6  # 2 grid x 3 samples
    assert gen.next_configs() is None
    assert {c["lr"] for c in configs} == {0.1, 0.01}
    for c in configs:
        assert 0 <= c["wd"] <= 1
        assert c["layers"] in (1, 2, 3)


def test_nested_space_and_loguniform():
    space = {"opt": {"lr": tune.loguniform(1e-5, 1e-1)},
             "fixed": "adam"}
    cfgs = BasicVariantGenerator(space, num_samples=4, seed=1).next_configs()
    assert len(cfgs) == 4
    for c in cfgs:
        assert 1e-5 <= c["opt"]["lr"] <= 1e-1
        assert c["fixed"] == "adam"


def test_asha_decisions():
    class T:
        trial_id = "a"

    sched = AsyncHyperBandScheduler(grace_period=1, reduction_factor=2,
                                    max_t=8)
    sched.set_metric("score", "max")
    # First trial at the rung always continues.
    assert sched.on_result(T(), {"training_iteration": 1,
                                 "score": 10}) == CONTINUE
    # A much worse second trial at the same rung stops.
    t2 = type("T2", (), {"trial_id": "b"})()
    assert sched.on_result(t2, {"training_iteration": 1,
                                "score": 1}) == STOP
    # max_t reached -> stop.
    assert sched.on_result(T(), {"training_iteration": 8,
                                 "score": 100}) == STOP


def test_pbt_exploit_directive():
    sched = PopulationBasedTraining(
        perturbation_interval=2,
        hyperparam_mutations={"lr": [0.1, 0.01]},
        quantile_fraction=0.5, seed=0)
    sched.set_metric("score", "max")

    class Trial:
        def __init__(self, tid, cfg):
            self.trial_id = tid
            self.config = cfg

    good = Trial("good", {"lr": 0.1})
    bad = Trial("bad", {"lr": 0.5})
    assert sched.on_result(good, {"training_iteration": 2,
                                  "score": 100}) == CONTINUE
    out = sched.on_result(bad, {"training_iteration": 2, "score": 1})
    assert isinstance(out, ExploitDirective)
    assert out.source_trial_id == "good"
    assert out.new_config["lr"] in (0.1, 0.01)


# -- end-to-end -------------------------------------------------------------


def _objective(config):
    score = 0.0
    for i in range(5):
        score += config["x"]
        tune.report({"score": score})


def test_tuner_function_trainable(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="fn_exp", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["score"] == 15.0
    assert not grid.errors
    assert os.path.exists(tmp_path / "fn_exp" / "experiment_state.json")


class _Quadratic(tune.Trainable):
    def setup(self, config):
        self.x = config["x"]
        self.val = 0.0

    def step(self):
        self.val += self.x * (10 - self.val) * 0.1
        return {"score": self.val, "done": self.val > 9.0}

    def save_checkpoint(self, path):
        with open(os.path.join(path, "state.txt"), "w") as f:
            f.write(str(self.val))

    def load_checkpoint(self, path):
        with open(os.path.join(path, "state.txt")) as f:
            self.val = float(f.read())


def test_tuner_class_trainable_with_checkpoints(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _Quadratic,
        param_space={"x": tune.grid_search([0.5, 1.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    checkpoint_freq=5),
        run_config=RunConfig(name="cls_exp", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    best = grid.get_best_result()
    assert best.metrics["score"] > 9.0
    assert best.checkpoint is not None
    assert os.path.exists(best.checkpoint.path)


def _early_stop_objective(config):
    for i in range(20):
        tune.report({"loss": config["lr"] * (i + 1)})


def test_tuner_with_asha(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _early_stop_objective,
        param_space={"lr": tune.grid_search([1.0, 2.0, 3.0, 4.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min",
            scheduler=tune.ASHAScheduler(grace_period=2,
                                         reduction_factor=2, max_t=20),
            max_concurrent_trials=2),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
    assert iters[0] < 20  # someone was early-stopped


def _resumable(config):
    start = 0
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "it.txt")) as f:
            start = int(f.read())
    for i in range(start, 6):
        d = os.path.join(tune.get_trial_dir(), f"ck_{i}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "it.txt"), "w") as f:
            f.write(str(i + 1))
        from ray_tpu.train.checkpoint import Checkpoint

        tune.report({"it": i + 1}, checkpoint=Checkpoint(d))
        if config.get("crash_at") == i + 1:
            raise RuntimeError("boom")


def test_tuner_restore_resumes_from_checkpoint(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _resumable,
        param_space={"crash_at": tune.grid_search([3])},
        tune_config=tune.TuneConfig(metric="it", mode="max"),
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert grid.errors  # first run crashed at it=3
    restored = tune.Tuner.restore(
        str(tmp_path / "resume"), _resumable,
        tune_config=tune.TuneConfig(metric="it", mode="max"))
    grid2 = restored.fit()
    best = grid2.get_best_result()
    assert best.metrics["it"] == 6
    assert not grid2.errors


class _Counter(tune.Trainable):
    def setup(self, config):
        self.i = 0

    def step(self):
        self.i += 1
        return {"iters": self.i}


def test_stop_criteria(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _Counter,
        param_space={},
        tune_config=tune.TuneConfig(metric="iters", mode="max"),
        run_config=RunConfig(name="stopc", storage_path=str(tmp_path),
                             stop={"training_iteration": 7}),
    )
    grid = tuner.fit()
    assert grid.get_best_result().metrics["training_iteration"] == 7


class _PBTTrainable(tune.Trainable):
    def setup(self, config):
        self.lr = config["lr"]
        self.score = 0.0

    def step(self):
        # Good lr (1.0) improves fast; bad lr (0.0) doesn't improve.
        self.score += self.lr
        return {"score": self.score,
                "done": self.score >= 20 or False}

    def save_checkpoint(self, path):
        with open(os.path.join(path, "s.txt"), "w") as f:
            f.write(f"{self.score},{self.lr}")

    def load_checkpoint(self, path):
        with open(os.path.join(path, "s.txt")) as f:
            s, _lr = f.read().split(",")
            self.score = float(s)


def test_pbt_end_to_end(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _PBTTrainable,
        param_space={"lr": tune.grid_search([0.0, 1.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.PopulationBasedTraining(
                perturbation_interval=4,
                hyperparam_mutations={"lr": [0.5, 1.0]},
                quantile_fraction=0.5, seed=0),
        ),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    # The lr=0 trial must have exploited the lr=1 trial's checkpoint:
    # both trials end with a meaningful score.
    scores = sorted(r.metrics["score"] for r in grid)
    assert scores[0] > 4.0  # a pure lr=0 trial would stay at 0


def test_trial_failure_retry(tune_cluster, tmp_path):
    import tempfile

    marker_dir = tempfile.mkdtemp()

    def flaky(config):
        marker = os.path.join(marker_dir, "attempted")
        if not os.path.exists(marker):
            open(marker, "w").close()
            raise RuntimeError("first attempt fails")
        tune.report({"ok": 1.0})

    from ray_tpu.train.config import FailureConfig

    tuner = tune.Tuner(
        flaky,
        param_space={},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
        run_config=RunConfig(name="flaky", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert grid.get_best_result().metrics["ok"] == 1.0


def test_trial_failure_retry_resumes_from_checkpoint(tune_cluster,
                                                     tmp_path):
    """RunConfig.failure_config at trial level: the retried trial
    restores the trial's latest checkpoint instead of restarting from
    scratch (a _resumable that crashed at it=3 finishes without ever
    re-reporting it=1)."""
    from ray_tpu.train.config import FailureConfig

    tuner = tune.Tuner(
        _resumable,
        param_space={"crash_at": tune.grid_search([3])},
        tune_config=tune.TuneConfig(metric="it", mode="max"),
        run_config=RunConfig(
            name="retry_resume", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1,
                                         restart_backoff_s=0.1)),
    )
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["it"] == 6
    # The retry resumed at it=3 (checkpoint from the crashing report):
    # its history never revisits the early iterations.
    retried = [m["it"] for m in best.metrics_history]
    assert retried.count(1) == 1
    assert retried[-1] == 6


# -- HyperBand (synchronous brackets) ---------------------------------------


def _fake_trial(tid):
    return type("T", (), {"trial_id": tid})()


def test_hyperband_bracket_shapes():
    from ray_tpu.tune.schedulers import HyperBandScheduler

    sched = HyperBandScheduler(max_t=9, reduction_factor=3)
    sched.set_metric("score", "max")
    # s_max = 2: bracket sizes 9 (r=1), 5 (r=3), 3 (r=9).
    trials = [_fake_trial(f"t{i}") for i in range(17)]
    for t in trials:
        sched.on_trial_add(t)
    caps = [b.capacity for b in sched._brackets]
    assert caps == [9, 5, 3]
    assert [b.r0 for b in sched._brackets] == [1, 3, 9]


def test_hyperband_pause_halve_resume():
    from ray_tpu.tune.schedulers import (
        PAUSE, RESUME, HyperBandScheduler)

    sched = HyperBandScheduler(max_t=9, reduction_factor=3)
    sched.set_metric("score", "max")
    trials = [_fake_trial(f"t{i}") for i in range(9)]
    for t in trials:
        sched.on_trial_add(t)
    # All 9 trials reach milestone 1 -> all pause.
    for i, t in enumerate(trials):
        assert sched.on_result(
            t, {"training_iteration": 1, "score": float(i)}) == PAUSE
    actions = sched.paused_actions(trials)
    # Top 3 by score resume, 6 stop.
    resumed = {tid for tid, a in actions.items() if a == RESUME}
    stopped = {tid for tid, a in actions.items() if a == STOP}
    assert resumed == {"t6", "t7", "t8"}
    assert len(stopped) == 6
    for tid in stopped:
        sched.on_trial_complete(_fake_trial(tid), None)
    # Next milestone is 3; survivors continue below it.
    t8 = trials[8]
    assert sched.on_result(
        t8, {"training_iteration": 2, "score": 9.0}) == CONTINUE
    assert sched.on_result(
        t8, {"training_iteration": 3, "score": 9.0}) == PAUSE
    for t in (trials[6], trials[7]):
        sched.on_result(t, {"training_iteration": 3, "score": 1.0})
    actions = sched.paused_actions(trials[6:])
    assert actions["t8"] == RESUME
    # Final rung: milestone == max_t -> STOP when reached.
    assert sched.on_result(
        t8, {"training_iteration": 9, "score": 9.0}) == STOP


def test_hyperband_underfilled_bracket_halves():
    from ray_tpu.tune.schedulers import PAUSE, RESUME, HyperBandScheduler

    sched = HyperBandScheduler(max_t=9, reduction_factor=3)
    sched.set_metric("score", "max")
    trials = [_fake_trial(f"t{i}") for i in range(4)]  # bracket cap is 9
    for t in trials:
        sched.on_trial_add(t)
    for i, t in enumerate(trials):
        assert sched.on_result(
            t, {"training_iteration": 1, "score": float(i)}) == PAUSE
    # The bracket is underfilled, so it waits for more trials ...
    assert sched.paused_actions(trials) == {}
    # ... until the search is exhausted, then halves with what it has.
    sched.on_search_exhausted()
    actions = sched.paused_actions(trials)
    assert actions["t3"] == RESUME
    assert sum(1 for a in actions.values() if a == STOP) == 3


class _CkptTrainable(tune.Trainable):
    def setup(self, config):
        self.x = config["x"]
        self.total = 0.0

    def step(self):
        self.total += self.x
        return {"score": self.total}

    def save_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "state"), "w") as f:
            f.write(str(self.total))

    def load_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "state")) as f:
            self.total = float(f.read())


def test_tuner_with_hyperband(tune_cluster, tmp_path):
    tuner = tune.Tuner(
        _CkptTrainable,
        param_space={"x": tune.grid_search([1, 2, 3, 4, 5, 6])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=tune.HyperBandScheduler(max_t=9,
                                              reduction_factor=3),
            max_concurrent_trials=3,
        ),
        run_config=RunConfig(name="hb", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    # x=6 dominates at every rung, so it must survive to max_t.
    assert best.metrics["score"] == pytest.approx(54.0)
    # Early-stopped trials did fewer than max_t iterations.
    iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
    assert iters[0] < 9
    assert iters[-1] == 9


# -- Searcher adapter --------------------------------------------------------


class _GreedySearcher(tune.Searcher):
    """Suggests x from a pool, then exploits the best observed so far."""

    def __init__(self):
        super().__init__(metric="score", mode="max")
        self.pool = [1.0, 5.0, 2.0]
        self.observed = {}
        self.suggested = {}
        self.completed = []

    def suggest(self, trial_id):
        if len(self.suggested) > len(self.completed):
            return None  # sequential: one outstanding suggestion
        if self.pool:
            x = self.pool.pop(0)
        elif self.observed:
            # Refine around the best seen so far.
            best_sid = max(self.observed, key=self.observed.get)
            x = self.suggested[best_sid] + 1.0
        else:
            return None
        self.suggested[trial_id] = x
        return {"x": x}

    def on_trial_complete(self, trial_id, result=None, error=False):
        self.completed.append(trial_id)
        if result and "score" in result:
            self.observed[trial_id] = result["score"]


def test_searcher_adapter_drives_trials(tune_cluster, tmp_path):
    searcher = _GreedySearcher()
    tuner = tune.Tuner(
        _objective,
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=5,
            search_alg=searcher, max_concurrent_trials=1,
        ),
        run_config=RunConfig(name="searcher", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert len(grid) == 5
    # Feedback reached the searcher under its own suggestion ids.
    assert len(searcher.completed) == 5
    assert all(t.startswith("suggest_") for t in searcher.completed)
    # The exploitation step built on the best observed trial (x=5 ->
    # refinements 6, 7; scores are 5*x).
    xs = sorted(searcher.suggested.values())
    assert xs == [1.0, 2.0, 5.0, 6.0, 7.0]
    assert grid.get_best_result().metrics["score"] == pytest.approx(35.0)


def test_search_generator_exhausts_with_finished():
    from ray_tpu.tune.search import SearchGenerator

    class Two(tune.Searcher):
        def __init__(self):
            super().__init__()
            self.n = 0

        def suggest(self, trial_id):
            if self.n >= 2:
                return tune.Searcher.FINISHED
            self.n += 1
            return {"x": self.n}

    gen = SearchGenerator(Two(), num_samples=10)
    cfgs = gen.next_configs()
    assert cfgs == [{"x": 1}, {"x": 2}]
    assert gen.next_configs() is None


def test_concurrency_limiter_wraps_bare_searcher(tune_cluster, tmp_path):
    from ray_tpu.tune.search import SearchGenerator

    class Fixed(tune.Searcher):
        def __init__(self):
            super().__init__()
            self.done = []

        def suggest(self, trial_id):
            return {"x": 2.0}

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.done.append(trial_id)

    searcher = Fixed()
    limiter = tune.ConcurrencyLimiter(searcher, max_concurrent=2)
    assert isinstance(limiter.searcher, SearchGenerator)
    tuner = tune.Tuner(
        _objective,
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=3,
            search_alg=limiter,
        ),
        run_config=RunConfig(name="limiter", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert len(grid) == 3  # TuneConfig.num_samples reached the generator
    assert len(searcher.done) == 3


# -- callbacks / loggers -----------------------------------------------------


def test_logger_callbacks_write_files(tune_cluster, tmp_path):
    events = []

    class Recorder(tune.Callback):
        def on_trial_start(self, it, trials, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, it, trials, trial, result):
            events.append(("result", trial.trial_id,
                           result["score"]))

        def on_trial_complete(self, it, trials, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, trials):
            events.append(("end", len(trials)))

    tuner = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="cb", storage_path=str(tmp_path),
            callbacks=[Recorder(), tune.CSVLoggerCallback(),
                       tune.JsonLoggerCallback()]),
    )
    grid = tuner.fit()
    assert not grid.errors
    kinds = [e[0] for e in events]
    assert kinds.count("start") == 2
    assert kinds.count("complete") == 2
    assert kinds[-1] == "end"
    assert sum(1 for k in kinds if k == "result") == 10  # 2 trials x 5
    # Files on disk per trial.
    import csv as csv_mod
    import glob as glob_mod
    import json as json_mod

    trial_dirs = sorted(
        d for d in glob_mod.glob(str(tmp_path / "cb" / "trial_*"))
        if os.path.isdir(d))
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        with open(os.path.join(d, "progress.csv")) as f:
            rows = list(csv_mod.DictReader(f))
        assert len(rows) == 5
        assert "score" in rows[0]
        with open(os.path.join(d, "result.json")) as f:
            lines = [json_mod.loads(line) for line in f]
        assert len(lines) == 5
        with open(os.path.join(d, "params.json")) as f:
            params = json_mod.load(f)
        assert params["x"] in (1.0, 2.0)


def test_callback_failure_does_not_break_experiment(tune_cluster,
                                                    tmp_path):
    class Broken(tune.Callback):
        def on_trial_result(self, *a):
            raise RuntimeError("callback bug")

    grid = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="cbfail", storage_path=str(tmp_path),
                             callbacks=[Broken()]),
    ).fit()
    assert not grid.errors
    assert grid.get_best_result().metrics["score"] == 5.0


# -- PB2 ---------------------------------------------------------------------


def test_pb2_exploit_uses_gp_within_bounds():
    sched = tune.PB2(
        hyperparam_bounds={"lr": (0.01, 1.0)},
        perturbation_interval=2, quantile_fraction=0.5, seed=0)
    sched.set_metric("score", "max")

    class T:
        def __init__(self, tid, cfg):
            self.trial_id = tid
            self.config = cfg

    good = T("good", {"lr": 0.9})
    bad = T("bad", {"lr": 0.05})
    # Feed several windows so observations accumulate.
    out = None
    for t in range(1, 9):
        sched.on_result(good, {"training_iteration": t,
                               "score": 10.0 * t})
        out = sched.on_result(bad, {"training_iteration": t,
                                    "score": 0.1 * t})
    assert isinstance(out, ExploitDirective)
    assert out.source_trial_id == "good"
    assert 0.01 <= out.new_config["lr"] <= 1.0
    # Observations were recorded for the GP (the exploited trial's
    # window is re-baselined, so only clean windows count).
    assert len(sched._obs_y) >= 3


def test_pb2_end_to_end(tune_cluster, tmp_path):
    def trainable(config):
        from ray_tpu.tune import session as ts

        lr = config["lr"]
        total = 0.0
        for i in range(12):
            total += 1.0 - abs(lr - 0.5)  # best lr = 0.5
            tune.report({"score": total,
                         "lr": lr})

    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.uniform(0.01, 1.0)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=4,
            scheduler=tune.PB2(hyperparam_bounds={"lr": (0.01, 1.0)},
                               perturbation_interval=3,
                               quantile_fraction=0.5, seed=0),
        ),
        run_config=RunConfig(name="pb2", storage_path=str(tmp_path)),
    )
    results = grid.fit()
    assert not results.errors
    assert results.get_best_result().metrics["score"] > 0
