"""Lockdep witness (ray_tpu/util/locks.py): ABBA inversion detection,
strict vs recording mode, reentrant locks, and the make_lock production
fast path."""

import threading

import pytest

from ray_tpu.util import locks
from ray_tpu.util import flight_recorder as fr


@pytest.fixture(autouse=True)
def _fresh_witness(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKDEP", "1")
    monkeypatch.setenv("RAY_TPU_LOCKDEP_STRICT", "1")
    locks.reset_witness_for_testing()
    yield
    locks.reset_witness_for_testing()


def test_make_lock_plain_when_disabled(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKDEP", "0")
    lock = locks.make_lock("x")
    assert not isinstance(lock, locks.WitnessLock)
    with lock:
        pass


def test_make_lock_witness_when_enabled():
    lock = locks.make_lock("x")
    assert isinstance(lock, locks.WitnessLock)


def test_consistent_order_is_clean():
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert locks.witness_graph() == {"A": ["B"]}


def test_abba_inversion_raises_in_strict_mode():
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locks.LockOrderInversion) as ei:
            with a:
                pass
    assert "A" in str(ei.value) and "B" in str(ei.value)


def test_abba_inversion_across_threads():
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    with a:
        with b:
            pass

    caught = []

    def other():
        try:
            with b:
                with a:
                    pass
        except locks.LockOrderInversion as e:
            caught.append(e)

    t = threading.Thread(target=other)
    t.start()
    t.join(timeout=10)
    assert len(caught) == 1


def test_three_lock_cycle_detected():
    a, b, c = (locks.WitnessLock(n) for n in "ABC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(locks.LockOrderInversion):
            with a:
                pass


def test_nonstrict_records_instead_of_raising(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKDEP_STRICT", "0")
    fr.reset_for_testing(capacity=32)
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    with a:
        with b:
            pass
    with b:
        with a:  # recorded, not raised
            pass
    events = [e for e in fr.snapshot() if e["event"] == "inversion"]
    assert len(events) == 1
    assert events[0]["severity"] == "error"
    tags = events[0]["tags"]
    assert tags["holding"] == "B" and tags["acquiring"] == "A"
    assert "A" in tags["cycle"] and "B" in tags["cycle"]
    fr.reset_for_testing()


def test_inversion_reported_once_per_pair(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKDEP_STRICT", "0")
    fr.reset_for_testing(capacity=32)
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    with a:
        with b:
            pass
    for _ in range(5):
        with b:
            with a:
                pass
    events = [e for e in fr.snapshot() if e["event"] == "inversion"]
    assert len(events) == 1
    fr.reset_for_testing()


def test_self_deadlock_raises_even_in_record_only_mode(monkeypatch):
    # Re-acquiring a non-reentrant lock in the same thread would block
    # on ourselves forever — the witness raises instead of hanging,
    # regardless of strict mode.
    monkeypatch.setenv("RAY_TPU_LOCKDEP_STRICT", "0")
    a = locks.WitnessLock("A")
    with a:
        with pytest.raises(locks.LockOrderInversion,
                           match="self-deadlock"):
            a.acquire()


def test_record_only_is_the_default(monkeypatch):
    # Enabling the witness alone must never crash the runtime: with
    # STRICT unset, an inversion is recorded, not raised.
    monkeypatch.delenv("RAY_TPU_LOCKDEP_STRICT", raising=False)
    fr.reset_for_testing(capacity=32)
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert [e for e in fr.snapshot() if e["event"] == "inversion"]
    fr.reset_for_testing()


def test_trylock_skips_order_check():
    # A non-blocking acquire can never deadlock (kernel-lockdep rule):
    # even an order that would invert is permitted and adds no edge.
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    with a:
        with b:
            pass
    with b:
        assert a.acquire(blocking=False)
        a.release()
    assert "A" not in locks.witness_graph().get("B", [])


def test_reentrant_lock_no_self_edge():
    r = locks.WitnessLock("R", reentrant=True)
    with r:
        with r:  # legal re-entry, not an ordering event
            pass
    assert locks.witness_graph() == {}


def test_explicit_acquire_release_and_out_of_order_release():
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    a.acquire()
    b.acquire()
    a.release()  # out-of-order release is legal
    b.release()
    assert locks.witness_graph() == {"A": ["B"]}
    # Held-stack is clean: acquiring in the other order now closes the
    # cycle (B held, A wanted).
    b.acquire()
    with pytest.raises(locks.LockOrderInversion):
        a.acquire()
    b.release()


def test_trylock_failure_does_not_track_as_held():
    a = locks.WitnessLock("A")
    b = locks.WitnessLock("B")
    assert a.acquire()

    def other():
        # Failed try-acquire must not leave A on this thread's held
        # stack — otherwise the b acquisition would add a phantom
        # A->B edge.
        assert a.acquire(blocking=False) is False
        with b:
            pass

    t = threading.Thread(target=other)
    t.start()
    t.join(timeout=10)
    a.release()
    assert "B" not in locks.witness_graph().get("A", [])
