"""Device-native object plane: sharded jax.Array put/get without host
bounces (core/device_objects.py), plus the serialization container-type
regression and the train→serve weight handoff.

Runs on the tier-1 virtual 8-device CPU mesh (conftest): the "device"
plane exercises the same per-shard protocol against CPU devices.
"""

import collections
import dataclasses
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import ray_tpu
from ray_tpu.core import device_objects, serialization
from ray_tpu.core.ids import ObjectID


# ---------------------------------------------------------------------------
# serialization container regression (satellite: _map_jax_arrays used to
# collapse namedtuples to plain tuples)
# ---------------------------------------------------------------------------

Point = collections.namedtuple("Point", ["x", "y"])


@dataclasses.dataclass
class Carrier:
    name: str
    payload: object


def _roundtrip(value):
    obj = serialization.serialize(value)
    return serialization.deserialize(obj.metadata, obj.inband, obj.buffers)


def test_namedtuple_type_preserved_through_jax_mapping():
    value = Point(x=jnp.ones((4,)), y=2)
    out = _roundtrip(value)
    assert type(out).__name__ == "Point"
    assert out._fields == ("x", "y")  # the old tuple(...) rebuild lost these
    assert isinstance(out.x, np.ndarray)
    assert out.y == 2


def test_dataclass_type_preserved_through_jax_mapping():
    value = Carrier(name="w", payload={"a": jnp.arange(3.0)})
    out = _roundtrip(value)
    assert isinstance(out, Carrier)
    assert out.name == "w"
    assert isinstance(out.payload["a"], np.ndarray)


def test_map_tree_identity_when_unchanged():
    value = {"a": [1, 2, (3, 4)], "b": Point(1, 2)}
    out = serialization.map_tree(value,
                                 lambda x: serialization.UNCHANGED)
    assert out is value


def test_map_tree_nested_namedtuple_in_list():
    value = [Point(jnp.zeros((2,)), "k"), {"p": Point(1, jnp.ones(()))}]
    out = serialization._map_jax_arrays(value)
    assert type(out[0]).__name__ == "Point"
    assert isinstance(out[0].x, np.ndarray)
    assert type(out[1]["p"]).__name__ == "Point"


# ---------------------------------------------------------------------------
# descriptors / local registry units (no cluster)
# ---------------------------------------------------------------------------

def _sharded(shape=(64, 32), spec=P("data", "model"), mesh_shape=(4, 2),
             dtype=jnp.float32, value=None):
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(mesh_shape),
                ("data", "model")[:len(mesh_shape)])
    if value is None:
        n = 1
        for d in shape:
            n *= d
        value = jnp.arange(n, dtype=dtype).reshape(shape)
    return jax.device_put(value, NamedSharding(mesh, spec))


def test_descriptor_roundtrip_named_sharding():
    arr = _sharded()
    desc = device_objects._describe(arr)
    assert desc["kind"] == device_objects.KIND_NAMED
    assert desc["global_shape"] == [64, 32]
    assert desc["mesh_axes"] == ["data", "model"]
    assert len(desc["shards"]) == 8
    sharding, device_keys = device_objects.build_sharding(desc)
    assert sharding.spec == P("data", "model")
    assert len(device_keys) == 8


def test_descriptor_replicated_axis_dedups_shards():
    arr = _sharded(spec=P("data", None))  # model axis replicated
    desc = device_objects._describe(arr)
    # 8 addressable shards but only 4 unique data pieces.
    assert len(desc["shards"]) == 4


def test_assemble_leaf_matches_original():
    arr = _sharded()
    desc = device_objects._describe(arr)
    oid = ObjectID(b"\x01" * 20)
    shard_bytes = {}
    for shard in arr.addressable_shards:
        norm = device_objects._norm_index(shard.index, arr.shape)
        tkey = tuple(tuple(p) for p in norm)
        for s in desc["shards"]:
            if tuple(tuple(p) for p in s["index"]) == tkey:
                shard_bytes[s["key"]] = bytes(
                    device_objects._host_view(shard.data))
    rebuilt = device_objects.assemble_leaf(desc, shard_bytes)
    assert rebuilt.sharding.spec == arr.sharding.spec
    np.testing.assert_array_equal(np.asarray(rebuilt), np.asarray(arr))
    assert oid  # silence lint


def test_bfloat16_shard_views_roundtrip():
    arr = _sharded(dtype=jnp.bfloat16,
                   value=jnp.ones((64, 32), jnp.bfloat16))
    desc = device_objects._describe(arr)
    assert desc["dtype"] == "bfloat16"
    shard = arr.addressable_shards[0]
    view = device_objects._host_view(shard.data)
    assert view.nbytes == shard.data.nbytes
    rebuilt = np.frombuffer(bytes(view), dtype=np.uint8).view(
        device_objects._np_dtype("bfloat16")).reshape(shard.data.shape)
    np.testing.assert_array_equal(rebuilt, np.asarray(shard.data))


# ---------------------------------------------------------------------------
# cluster round trips
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_same_process_get_is_by_reference(cluster):
    arr = _sharded()
    ref = ray_tpu.put({"w": arr, "meta": Point(1, 2)})
    out = ray_tpu.get(ref, timeout=60)
    assert out["w"] is arr  # zero copies of any kind
    assert type(out["meta"]).__name__ == "Point"
    del ref


def test_cross_process_pull_preserves_sharding_and_values(cluster):
    arr = _sharded()
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def consume(x):
        import jax as _jax

        return {
            "type": type(x).__name__,
            "spec": str(x.sharding.spec),
            "mesh_axes": list(x.sharding.mesh.axis_names),
            "sum": float(x.sum()),
            "n_shards": len(list(x.addressable_shards)),
            "fully_addressable": bool(x.is_fully_addressable),
            "devices": len(_jax.devices()),
        }

    out = ray_tpu.get(consume.remote(ref), timeout=120)
    assert out["type"] == "ArrayImpl"
    assert out["spec"] == str(P("data", "model"))
    assert out["mesh_axes"] == ["data", "model"]
    assert out["sum"] == float(np.arange(64 * 32, dtype=np.float32).sum())
    assert out["n_shards"] == 8
    del ref


def test_small_arrays_stay_on_host_path(cluster):
    tiny = jnp.float32(3.0)  # below device_object_min_bytes
    ref = ray_tpu.put({"loss": tiny})
    obj = ray_tpu.api._require_worker().memory_store.get_if_exists(ref.id)
    assert obj.metadata == serialization.NORMAL
    out = ray_tpu.get(ref, timeout=60)
    assert float(out["loss"]) == 3.0
    del ref


def test_plane_disable_falls_back_to_numpy(cluster):
    cw = ray_tpu.api._require_worker()
    cw.config.device_object_plane_enabled = False
    try:
        ref = ray_tpu.put(_sharded())
        out = ray_tpu.get(ref, timeout=60)
        assert isinstance(out, np.ndarray)
        del ref
    finally:
        cw.config.device_object_plane_enabled = True


def test_mixed_pytree_shm_envelope(cluster):
    """Device leaves + a large host leaf: the envelope itself rides the
    shm plasma path, and the DEVICE metadata survives pack/parse so the
    consumer still resolves the device leaves."""
    arr = _sharded()
    filler = np.arange(300_000, dtype=np.float64)  # > shm threshold
    ref = ray_tpu.put({"w": arr, "filler": filler})
    out = ray_tpu.get(ref, timeout=60)
    assert out["w"] is arr
    np.testing.assert_array_equal(out["filler"], filler)

    @ray_tpu.remote
    def consume(d):
        return float(d["w"].sum()) + float(d["filler"][-1])

    expect = float(np.asarray(arr).sum()) + float(filler[-1])
    assert ray_tpu.get(consume.remote(ref), timeout=120) == expect
    del ref


def test_free_drops_registry_and_manifest(cluster):
    ref = ray_tpu.put(_sharded())
    hex_id = ref.hex()
    assert device_objects.holds(hex_id)
    del ref
    deadline = time.monotonic() + 10
    cw = ray_tpu.api._require_worker()
    while time.monotonic() < deadline and device_objects.holds(hex_id):
        cw.reference_counter._drain_deferred()
        time.sleep(0.05)
    assert not device_objects.holds(hex_id)


# ---------------------------------------------------------------------------
# train-puts / serve-gets round trip (the production win)
# ---------------------------------------------------------------------------

def test_train_puts_serve_gets_roundtrip(cluster):
    """A gang worker publishes a sharded pytree; a Serve replica
    cold-starts by fetching it. Sharding spec + values survive, and no
    whole-array host buffer is ever created on the consumer."""
    from ray_tpu import serve

    @ray_tpu.remote
    class GangWorker:
        def publish(self):
            import jax as _jax
            import jax.numpy as _jnp
            import numpy as _np
            from jax.sharding import (
                Mesh as _Mesh, NamedSharding as _NS,
                PartitionSpec as _P)

            from ray_tpu.serve import publish_weights

            mesh = _Mesh(_np.array(_jax.devices()[:8]).reshape(8),
                         ("data",))
            pytree = {
                "dense": _jax.device_put(
                    _jnp.arange(8 * 1024 * 64, dtype=_jnp.float32
                                ).reshape(8 * 1024, 64),
                    _NS(mesh, _P("data"))),
                "bias": _jax.device_put(
                    _jnp.ones((4096,), _jnp.float32), _NS(mesh, _P())),
                "step": 7,
            }
            _ref, version = publish_weights("m0", pytree)
            return version

    gang = GangWorker.remote()
    assert ray_tpu.get(gang.publish.remote(), timeout=120) == 1

    @serve.deployment(num_cpus=0.1)
    class Model:
        def __init__(self):
            from ray_tpu.core import device_objects as dob
            from ray_tpu.serve import fetch_weights

            self.weights = fetch_weights("m0", timeout=120)
            self.staging_peak = dob.peak_staging_bytes()

        def __call__(self, _request):
            w = self.weights["dense"]
            total = int(w.nbytes + self.weights["bias"].nbytes)
            return {
                "sum": float(w.sum()),
                "spec": str(w.sharding.spec),
                "mesh_axes": list(w.sharding.mesh.axis_names),
                "step": self.weights["step"],
                "total_bytes": total,
                "staging_peak": int(self.staging_peak),
            }

    h = serve.run(Model.bind(), name="weights_app", proxy=False)
    try:
        out = h.remote(None).result(timeout=120)
        dense_n = 8 * 1024 * 64
        # Sharded sum reduces per-shard partials: same value modulo
        # float32 accumulation order.
        assert out["sum"] == pytest.approx(
            float(np.arange(dense_n, dtype=np.float64).sum()), rel=1e-5)
        assert out["spec"] == str(P("data"))
        assert out["mesh_axes"] == ["data"]
        assert out["step"] == 7
        # The device plane's acceptance property: host staging stayed
        # shard-sized. A host-bounce path would have staged the whole
        # array (total_bytes) at once.
        assert 0 < out["staging_peak"] < out["total_bytes"]
    finally:
        serve.delete("weights_app")


def test_replica_cold_start_from_peer(cluster):
    """After the publisher dies, a new fetcher cold-starts from a PEER
    holder: the manifest + envelope in the head's owner table routes the
    per-shard pulls to the surviving replica."""
    from ray_tpu import serve

    @ray_tpu.remote
    class Publisher:
        def publish(self):
            import jax as _jax
            import jax.numpy as _jnp
            import numpy as _np
            from jax.sharding import (
                Mesh as _Mesh, NamedSharding as _NS,
                PartitionSpec as _P)

            from ray_tpu.serve import publish_weights

            mesh = _Mesh(_np.array(_jax.devices()[:8]).reshape(8),
                         ("data",))
            w = _jax.device_put(
                _jnp.full((2048, 32), 5.0, _jnp.float32),
                _NS(mesh, _P("data")))
            publish_weights("m1", {"w": w})
            return True

    pub = Publisher.remote()
    assert ray_tpu.get(pub.publish.remote(), timeout=120)

    @ray_tpu.remote
    class Replica:
        def __init__(self):
            from ray_tpu.serve import fetch_weights

            self.weights = fetch_weights("m1", timeout=120)

        def checksum(self):
            return float(self.weights["w"].sum())

    first = Replica.remote()
    expect = 2048 * 32 * 5.0
    assert ray_tpu.get(first.checksum.remote(), timeout=120) == expect

    # Kill the publisher: the owner (and original holder) is gone.
    ray_tpu.kill(pub)
    time.sleep(1.0)

    second = Replica.remote()  # must pull from `first`, the peer holder
    assert ray_tpu.get(second.checksum.remote(), timeout=120) == expect
    assert serve  # imported for parity with the serve-side test above


def test_donate_releases_producer_buffers(cluster):
    @ray_tpu.remote
    class Donor:
        def put(self):
            import jax as _jax
            import jax.numpy as _jnp
            import numpy as _np
            from jax.sharding import (
                Mesh as _Mesh, NamedSharding as _NS,
                PartitionSpec as _P)

            mesh = _Mesh(_np.array(_jax.devices()[:8]).reshape(8),
                         ("d",))
            self.w = _jax.device_put(
                _jnp.full((512, 64), 2.0, _jnp.float32),
                _NS(mesh, _P("d")))
            return [ray_tpu.put(self.w)]

        def holds(self, refs):
            from ray_tpu.core import device_objects as dob

            return dob.holds(refs[0].hex())

        def deleted(self):
            return bool(self.w.is_deleted())

    donor = Donor.remote()
    ref = ray_tpu.get(donor.put.remote(), timeout=120)[0]
    assert ray_tpu.get(donor.holds.remote([ref]), timeout=60)
    out = ray_tpu.get(ref, timeout=120, donate=True)
    assert float(out.sum()) == 512 * 64 * 2.0
    assert not ray_tpu.get(donor.holds.remote([ref]), timeout=60)
    assert ray_tpu.get(donor.deleted.remote(), timeout=60)
    # The consumer registered as a holder: the ref still resolves.
    again = ray_tpu.get(ref, timeout=60)
    assert float(again.sum()) == 512 * 64 * 2.0
    del ref
