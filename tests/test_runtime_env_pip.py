"""Pip runtime environments (reference strategy: runtime_env pip plugin
tests — conflicting dependency sets run concurrently on one node, env
cache is refcounted and GCed). Offline: wheels are hand-rolled zips
installed via --no-index --find-links."""

import base64
import hashlib
import os
import zipfile

import pytest

from ray_tpu.core import runtime_env_pip as rep


def _make_wheel(dirpath: str, name: str, version: str) -> str:
    """Hand-roll a valid py3-none-any wheel with one module exposing
    __version__ (no network, no build backend)."""
    wheel = os.path.join(dirpath, f"{name}-{version}-py3-none-any.whl")
    code = f'__version__ = "{version}"\n'
    dist = f"{name}-{version}.dist-info"
    metadata = (f"Metadata-Version: 2.1\nName: {name}\n"
                f"Version: {version}\n")
    wheel_meta = ("Wheel-Version: 1.0\nGenerator: ray-tpu-test\n"
                  "Root-Is-Purelib: true\nTag: py3-none-any\n")

    def record_line(path, data):
        digest = base64.urlsafe_b64encode(
            hashlib.sha256(data.encode()).digest()).rstrip(b"=").decode()
        return f"{path},sha256={digest},{len(data)}"

    record = "\n".join([
        record_line(f"{name}.py", code),
        record_line(f"{dist}/METADATA", metadata),
        record_line(f"{dist}/WHEEL", wheel_meta),
        f"{dist}/RECORD,,",
    ]) + "\n"
    with zipfile.ZipFile(wheel, "w") as z:
        z.writestr(f"{name}.py", code)
        z.writestr(f"{dist}/METADATA", metadata)
        z.writestr(f"{dist}/WHEEL", wheel_meta)
        z.writestr(f"{dist}/RECORD", record)
    return wheel


@pytest.fixture()
def wheel_house(tmp_path, monkeypatch):
    house = tmp_path / "wheels"
    house.mkdir()
    _make_wheel(str(house), "rtpu_testdep", "1.0.0")
    _make_wheel(str(house), "rtpu_testdep", "2.0.0")
    monkeypatch.setenv("RAY_TPU_PIP_FIND_LINKS", str(house))
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path / "sess"))
    return str(house)


def test_ensure_env_and_cache(wheel_house, tmp_path):
    sp1 = rep.ensure_env(["rtpu_testdep==1.0.0"])
    assert os.path.isdir(sp1)
    assert os.path.exists(os.path.join(sp1, "rtpu_testdep.py"))
    # Idempotent: second call reuses the ready env.
    assert rep.ensure_env(["rtpu_testdep==1.0.0"]) == sp1
    # Different deps, different env.
    sp2 = rep.ensure_env(["rtpu_testdep==2.0.0"])
    assert sp2 != sp1


def test_pip_context_isolates_and_unloads(wheel_house):
    import sys

    with rep.PipEnvContext(["rtpu_testdep==1.0.0"]):
        import rtpu_testdep

        assert rtpu_testdep.__version__ == "1.0.0"
    assert "rtpu_testdep" not in sys.modules
    with rep.PipEnvContext(["rtpu_testdep==2.0.0"]):
        import rtpu_testdep

        assert rtpu_testdep.__version__ == "2.0.0"
    assert "rtpu_testdep" not in sys.modules


def test_gc_unused_respects_refcounts(wheel_house):
    rep.ensure_env(["rtpu_testdep==1.0.0"])
    rep.ensure_env(["rtpu_testdep==2.0.0"])
    with rep.PipEnvContext(["rtpu_testdep==1.0.0"]):
        deleted = rep.gc_unused(max_envs=0)
        # The active env survives; the idle one is collectable.
        live = rep.env_dir(["rtpu_testdep==1.0.0"])
        assert live not in deleted
        assert os.path.isdir(live)


def test_conflicting_pip_envs_concurrently(wheel_house):
    """Two tasks with CONFLICTING pip deps run concurrently on one
    node: the env hash is part of the scheduling key, so they land on
    different workers, each importing its own version."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:

        @ray_tpu.remote(runtime_env={"pip": ["rtpu_testdep==1.0.0"]})
        def v1():
            import time

            import rtpu_testdep

            time.sleep(0.5)  # force temporal overlap with v2
            return rtpu_testdep.__version__

        @ray_tpu.remote(runtime_env={"pip": ["rtpu_testdep==2.0.0"]})
        def v2():
            import time

            import rtpu_testdep

            time.sleep(0.5)
            return rtpu_testdep.__version__

        out = ray_tpu.get([v1.remote(), v2.remote(),
                           v1.remote(), v2.remote()], timeout=240)
        assert out == ["1.0.0", "2.0.0", "1.0.0", "2.0.0"]
    finally:
        ray_tpu.shutdown()
