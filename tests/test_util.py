"""Tests for ray_tpu.util: actor pool, queue, metrics, state API,
timeline, chaos (reference strategy: python/ray/tests/test_actor_pool.py,
test_queue.py, test_metrics_agent.py, util/state tests)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue, timeline
from ray_tpu.util import metrics as um
from ray_tpu.util import state as ust


@pytest.fixture(scope="module")
def util_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


class _Doubler:
    def double(self, x):
        return x * 2

    def slow_double(self, x):
        time.sleep(0.05)
        return x * 2


def test_actor_pool_map(util_cluster):
    actors = [ray_tpu.remote(_Doubler).options(num_cpus=0.5).remote()
              for _ in range(2)]
    pool = ActorPool(actors)
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [i * 2 for i in range(8)]
    out2 = sorted(pool.map_unordered(
        lambda a, v: a.slow_double.remote(v), range(6)))
    assert out2 == [i * 2 for i in range(6)]
    for a in actors:
        ray_tpu.kill(a)


def test_actor_pool_submit_get_next(util_cluster):
    actors = [ray_tpu.remote(_Doubler).options(num_cpus=0.5).remote()]
    pool = ActorPool(actors)
    pool.submit(lambda a, v: a.double.remote(v), 1)
    pool.submit(lambda a, v: a.double.remote(v), 2)
    assert pool.get_next() == 2
    assert pool.get_next() == 4
    assert not pool.has_next()
    ray_tpu.kill(actors[0])


def test_queue_basic(util_cluster):
    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.empty()
    with pytest.raises(Exception):
        q.get(block=False)
    q.shutdown()


def test_queue_batch_and_full(util_cluster):
    from ray_tpu.util import Full

    q = Queue(maxsize=3)
    n = q.put_nowait_batch([1, 2, 3, 4])
    assert n == 3
    with pytest.raises(Full):
        q.put(9, block=False)
    assert q.get_nowait_batch(10) == [1, 2, 3]
    q.shutdown()


def test_queue_producer_consumer(util_cluster):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return n

    @ray_tpu.remote
    def consumer(q, n):
        return sum(q.get(timeout=30) for _ in range(n))

    pref = producer.remote(q, 10)
    cref = consumer.remote(q, 10)
    assert ray_tpu.get(cref, timeout=60) == 45
    assert ray_tpu.get(pref, timeout=60) == 10
    q.shutdown()


def test_metrics_counter_gauge_histogram(util_cluster):
    c = um.Counter("req_total", "requests", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(5, tags={"route": "/b"})
    g = um.Gauge("inflight", "in flight")
    g.set(7)
    h = um.Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    um.flush_metrics()
    merged = um.collect_metrics()
    vals = merged["req_total"]["values"]
    assert vals[(("route", "/a"),)] == 3
    assert vals[(("route", "/b"),)] == 5
    assert merged["inflight"]["values"][()] == 7
    hist = merged["latency_s"]["values"][()]
    assert hist[-1] == 3  # count
    assert abs(hist[-2] - 5.55) < 1e-6  # sum
    text = um.prometheus_text()
    assert "# TYPE req_total counter" in text
    assert 'req_total{route="/a"} 3' in text
    assert "latency_s_count 3" in text


def test_state_api(util_cluster):
    @ray_tpu.remote
    def named_task():
        return 1

    refs = [named_task.options(name="state_test_task").remote()
            for _ in range(3)]
    ray_tpu.get(refs, timeout=60)

    class StateActor:
        def ping(self):
            return "pong"

    a = ray_tpu.remote(StateActor).options(
        name="state_actor", num_cpus=0.1).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"

    actors = ust.list_actors()
    assert any(x.get("name") == "state_actor" and x["state"] == "ALIVE"
               for x in actors)
    workers = ust.list_workers()
    assert len(workers) >= 1
    nodes = ust.list_nodes()
    assert len(nodes) >= 1

    # Task events flush after <= ~1s.
    deadline = time.time() + 15
    while time.time() < deadline:
        tasks = ust.list_tasks()
        done = [t for t in tasks if t.get("name") == "state_test_task"
                and t["state"] == "FINISHED"]
        if len(done) >= 1:
            break
        time.sleep(0.3)
    assert done, f"no finished task events: {tasks[:5]}"
    summary = ust.summarize_tasks()
    assert "state_test_task" in summary
    ray_tpu.kill(a)


def test_timeline_export(util_cluster, tmp_path):
    @ray_tpu.remote
    def traced():
        time.sleep(0.02)
        return 1

    ray_tpu.get([traced.options(name="traced_task").remote()
                 for _ in range(2)], timeout=60)
    deadline = time.time() + 15
    trace = []
    while time.time() < deadline:
        trace = timeline()
        if any(ev["name"] == "traced_task" for ev in trace):
            break
        time.sleep(0.3)
    spans = [ev for ev in trace if ev["name"] == "traced_task"]
    assert spans and spans[0]["ph"] == "X"
    assert spans[0]["dur"] >= 0.02 * 1e6 * 0.5
    out = tmp_path / "timeline.json"
    timeline(str(out))
    assert out.exists()


def test_chaos_worker_killer(util_cluster):
    from ray_tpu.util.chaos import WorkerKiller

    @ray_tpu.remote
    def steady(x):
        time.sleep(0.2)
        return x

    killer = ray_tpu.remote(WorkerKiller).options(num_cpus=0.1).remote(
        kill_interval_s=0.3, max_kills=2)
    run_ref = killer.run.remote()
    # Tasks keep succeeding despite worker kills (retries).
    results = ray_tpu.get(
        [steady.options(max_retries=5).remote(i) for i in range(12)],
        timeout=240)
    assert results == list(range(12))
    ray_tpu.get(killer.stop.remote(), timeout=30)
    ray_tpu.kill(killer)
