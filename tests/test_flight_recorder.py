"""Flight recorder: ring semantics, the pinned event-name catalog, and
crash postmortems (no cluster needed — these are the tier-1 unit lanes;
the e2e debug plane lives in tests/test_debug_dump.py)."""

import json
import re
import threading

import ray_tpu
from ray_tpu.util import flight_recorder as fr


def teardown_function(_fn):
    fr.reset_for_testing()


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_bounded_and_overwrites_in_order():
    fr.reset_for_testing(capacity=8)
    for i in range(20):
        fr.record("sched", "lease_wait", i=i)
    events = fr.snapshot()
    assert len(events) == 8
    # Oldest entries were overwritten; survivors are the newest 20-8..19
    # in append order.
    assert [e["tags"]["i"] for e in events] == list(range(12, 20))
    assert all(e["subsystem"] == "sched" for e in events)
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)


def test_record_fields_and_severity():
    fr.reset_for_testing(capacity=16)
    fr.record("gcs", "node_dead", severity="error", node="abc123",
              detail=None, count=3, obj=object())
    (ev,) = fr.snapshot()
    assert ev["event"] == "node_dead"
    assert ev["severity"] == "error"
    assert ev["tags"]["node"] == "abc123"
    assert ev["tags"]["count"] == 3
    # Non-primitive tag values are coerced so the debug-dump RPC can
    # always serialize a snapshot.
    assert isinstance(ev["tags"]["obj"], str)


def test_snapshot_limit():
    fr.reset_for_testing(capacity=32)
    for i in range(10):
        fr.record("rpc", "retry", i=i)
    assert [e["tags"]["i"] for e in fr.snapshot(limit=3)] == [7, 8, 9]


def test_thread_safety_under_concurrent_append_and_snapshot():
    fr.reset_for_testing(capacity=128)
    errors = []
    stop = threading.Event()

    def writer(tid):
        try:
            for i in range(500):
                fr.record("rpc", "retry", tid=tid, i=i)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                for ev in fr.snapshot():
                    assert ev["subsystem"] == "rpc"
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(8)]
    snap_threads = [threading.Thread(target=reader) for _ in range(2)]
    for t in threads + snap_threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in snap_threads:
        t.join()
    assert not errors
    assert len(fr.snapshot()) == 128


def test_disabled_recorder_is_a_noop():
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old = cfg.flight_recorder_enabled
    cfg.flight_recorder_enabled = False
    try:
        fr.reset_for_testing(capacity=8)
        fr.record("sched", "lease_wait", i=1)
        assert fr.snapshot() == []
    finally:
        cfg.flight_recorder_enabled = old
        fr.reset_for_testing()


# ---------------------------------------------------------------------------
# the pinned (subsystem, event) catalog — the telemetry-catalog lint's
# sibling: call sites use literal names, so this static scan is exact.
# ---------------------------------------------------------------------------

_CALL_RE = re.compile(
    r"""(?:flight_recorder\.|_fr\(\)\.|(?<![\w.]))record\(\s*
        ['"]([a-z0-9_]+)['"]\s*,\s*['"]([a-z0-9_]+)['"]""",
    re.VERBOSE)


def _recorded_pairs():
    import pathlib

    pkg = pathlib.Path(ray_tpu.__file__).parent
    pairs = {}
    for path in pkg.rglob("*.py"):
        text = path.read_text()
        for m in _CALL_RE.finditer(text):
            pairs.setdefault((m.group(1), m.group(2)), []).append(
                str(path.relative_to(pkg)))
    return pairs


def test_catalog_names_conform():
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    assert fr.CATALOG, "catalog must not be empty"
    for subsystem, events in fr.CATALOG.items():
        assert name_re.match(subsystem), subsystem
        assert events, f"subsystem {subsystem} declares no events"
        assert len(set(events)) == len(events), (
            f"duplicate event names in {subsystem}")
        for event in events:
            assert name_re.match(event), f"{subsystem}/{event}"


def test_every_call_site_uses_a_catalog_name():
    pairs = _recorded_pairs()
    assert pairs, "no flight_recorder.record call sites found"
    stray = {p: files for p, files in pairs.items()
             if p[0] not in fr.CATALOG or p[1] not in fr.CATALOG[p[0]]}
    assert not stray, (
        "record() call sites outside flight_recorder.CATALOG "
        f"(add them to the catalog or fix the name): {stray}")


def test_every_catalog_event_has_a_call_site():
    """The reverse direction: a catalog entry nothing records is drift —
    either the call site was renamed (silently orphaning the name) or
    the event was removed without updating the pin."""
    pairs = set(_recorded_pairs())
    dead = [(s, e) for s, events in fr.CATALOG.items()
            for e in events if (s, e) not in pairs]
    assert not dead, f"catalog events never recorded anywhere: {dead}"


# ---------------------------------------------------------------------------
# postmortem + stacks
# ---------------------------------------------------------------------------

def test_dump_stacks_sees_this_thread():
    stacks = fr.dump_stacks()
    assert any("MainThread" in name for name in stacks)
    joined = "\n".join("\n".join(v) for v in stacks.values())
    assert "test_dump_stacks_sees_this_thread" in joined


def test_flush_postmortem(tmp_path):
    fr.reset_for_testing(capacity=32)
    fr.record("gcs", "node_dead", severity="error", node="deadbeef")
    path = fr.flush_postmortem("BoomError: synthetic", str(tmp_path))
    assert path is not None
    data = json.loads(open(path).read())
    assert data["reason"].startswith("BoomError")
    assert any(e["event"] == "node_dead" for e in data["events"])
    # The flush itself is recorded as evidence.
    assert any(e["event"] == "postmortem" for e in data["events"])
    assert data["stacks"]


# ---------------------------------------------------------------------------
# timeline merge (flight lanes ride next to task/telemetry lanes)
# ---------------------------------------------------------------------------

def test_timeline_merges_flight_lanes():
    from ray_tpu.util.timeline import timeline

    fr.reset_for_testing(capacity=32)
    fr.record("sched", "lease_wait", severity="warn", reason="no TPU")
    fr.record("train", "heartbeat_miss", severity="warn", rank=2)
    trace = timeline(events=[], include_telemetry=False)
    lanes = {ev["tid"] for ev in trace}
    assert "fr:sched" in lanes and "fr:train" in lanes
    hb = next(ev for ev in trace if ev["tid"] == "fr:train")
    assert hb["name"] == "heartbeat_miss"
    assert hb["args"]["rank"] == 2
    assert hb["args"]["severity"] == "warn"


# ---------------------------------------------------------------------------
# state-API satellite: the extended filter ops (pure function)
# ---------------------------------------------------------------------------

def test_apply_filters_extended_ops():
    from ray_tpu.util.state import _apply_filters

    rows = [
        {"name": "alpha", "state": "RUNNING", "dur": 1.5},
        {"name": "beta", "state": "FAILED", "dur": 9.0},
        {"name": "gamma", "state": "FINISHED", "dur": None},
    ]
    assert [r["name"] for r in _apply_filters(
        rows, [("state", "in", ("RUNNING", "FAILED"))])] == ["alpha",
                                                             "beta"]
    assert [r["name"] for r in _apply_filters(
        rows, [("name", "contains", "am")])] == ["gamma"]
    assert [r["name"] for r in _apply_filters(
        rows, [("dur", ">", 2)])] == ["beta"]
    # None / non-numeric rows never match numeric comparisons.
    assert [r["name"] for r in _apply_filters(
        rows, [("dur", "<", 2)])] == ["alpha"]
    # A row missing the key never matches 'in' (no TypeError against a
    # string collection).
    assert _apply_filters(rows, [("missing", "in", "abc")]) == []
    import pytest

    with pytest.raises(ValueError):
        _apply_filters(rows, [("name", "~", "x")])
