"""Tier-1 gate for the control-plane load lane's committed artifact
(BENCH_CONTROL_PLANE.json, written by ``bench.py control-plane``): the
newest artifact must parse and carry every schema key with a sane
value — a stale or hand-mangled JSON can't green the lane silently
(same pattern as the TSan artifact gate)."""

import glob
import json
import os
import re


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _churn(required_rate_key):
    def ok(v):
        return (isinstance(v, dict) and v.get("seconds", 0) > 0
                and v.get(required_rate_key, 0) > 0)
    return ok


def _handler_rows(v):
    if not isinstance(v, list) or not v:
        return False
    keys = {"method", "calls", "errors", "p50_ms", "p99_ms",
            "queue_p99_ms"}
    return all(keys <= set(row) and row["calls"] > 0
               and row["p99_ms"] >= row["p50_ms"] >= 0
               for row in v)


#: every key the artifact must carry, with a validity predicate.
_ARTIFACT_SCHEMA = {
    # The issue floor: a 25-50 logical-node fake cluster.
    "nodes": lambda v: isinstance(v, int) and v >= 25,
    "task_churn": _churn("tasks_per_second"),
    "actor_churn": _churn("actors_per_second"),
    "pubsub_churn": _churn("publishes_per_second"),
    "kv_churn": _churn("puts_per_second"),
    "handlers": _handler_rows,
    "handlers_tracked": lambda v: isinstance(v, int) and v >= 20,
    "rpc_calls_total": lambda v: isinstance(v, int) and v > 100,
    "loop_lag_p50_ms": lambda v: isinstance(v, (int, float)) and v >= 0,
    "loop_lag_p99_ms": lambda v: isinstance(v, (int, float)) and v >= 0,
    "loop_stalls": lambda v: isinstance(v, int) and v >= 0,
    "pubsub_fanout_max": lambda v: isinstance(v, int) and v >= 1,
    "kv_amplification_max": lambda v: isinstance(v, (int, float))
    and v >= 1.0,
    "fanout": lambda v: isinstance(v, dict)
    and {"pubsub", "kv", "pruned_subscribers"} <= set(v)
    and any(ns["ns"] == "metrics" and ns["amplification"] >= 2.0
            for ns in v["kv"]),
    "wall_s": lambda v: isinstance(v, (int, float)) and v > 0,
    "run_date": lambda v: isinstance(v, str)
    and re.fullmatch(r"\d{4}-\d{2}-\d{2}", v) is not None,
}


def _latest_artifact() -> str:
    paths = sorted(glob.glob(os.path.join(_REPO,
                                          "BENCH_CONTROL_PLANE*.json")))
    assert paths, "no BENCH_CONTROL_PLANE*.json artifact committed"
    return paths[-1]


def test_control_plane_artifact_schema():
    """Tier-1: the newest committed control-plane bench artifact parses
    and proves a real >=25-node run — every schema key present and
    valid, no unknown keys."""
    path = _latest_artifact()
    with open(path) as f:
        data = json.load(f)
    for key, ok in _ARTIFACT_SCHEMA.items():
        assert key in data, f"{os.path.basename(path)} missing {key!r}"
        assert ok(data[key]), (
            f"{os.path.basename(path)}: bad {key!r}: {data[key]!r}")
    extra = set(data) - set(_ARTIFACT_SCHEMA)
    assert not extra, f"unknown artifact keys (update the schema): {extra}"
