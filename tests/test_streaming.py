"""Streaming-generator task tests (reference strategy:
python/ray/tests/test_streaming_generator*.py)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def stream_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_streaming_basic(stream_cluster):
    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref, timeout=60)
           for ref in gen.options(num_returns="streaming").remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_streaming_incremental_delivery(stream_cluster):
    @ray_tpu.remote
    def slow_gen():
        for i in range(4):
            yield i
            time.sleep(0.4)

    g = slow_gen.options(num_returns="streaming").remote()
    t0 = time.time()
    first = ray_tpu.get(next(iter(g)), timeout=60)
    first_latency = time.time() - t0
    assert first == 0
    # The first item must arrive well before the task finishes (~1.6s).
    assert first_latency < 1.2, first_latency
    rest = [ray_tpu.get(r, timeout=60) for r in g]
    assert rest == [1, 2, 3]


def test_streaming_large_items_through_store(stream_cluster):
    @ray_tpu.remote
    def big_gen():
        for i in range(3):
            yield np.full((300_000,), i, dtype=np.float64)  # > inline

    vals = [ray_tpu.get(r, timeout=120)
            for r in big_gen.options(num_returns="streaming").remote()]
    assert [v[0] for v in vals] == [0.0, 1.0, 2.0]
    assert all(v.shape == (300_000,) for v in vals)


def test_streaming_error_mid_stream(stream_cluster):
    @ray_tpu.remote
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("stream exploded")

    g = bad_gen.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(g), timeout=60) == 1
    assert ray_tpu.get(next(g), timeout=60) == 2
    with pytest.raises(Exception, match="stream exploded"):
        next(g)  # the failure surfaces at end-of-stream


def test_streaming_pre_generator_failure_closes_stream(stream_cluster):
    @ray_tpu.remote
    def gen_bad_env():
        yield 1

    g = (gen_bad_env
         .options(num_returns="streaming",
                  runtime_env={"pip": ["requests"]})
         .remote())
    # pip envs are supported now; this one fails during SETUP (the
    # offline host can't resolve pypi), which is exactly the
    # pre-generator failure the test needs.
    with pytest.raises(Exception, match="runtime.?env"):
        next(g)  # setup error closes the stream instead of hanging


def test_streaming_on_actor_method_raises(stream_cluster):
    class A:
        def gen(self):
            yield 1

    a = ray_tpu.remote(A).options(num_cpus=0.1).remote()
    with pytest.raises(TypeError, match="streaming"):
        a.gen.options(num_returns="streaming").remote()
    ray_tpu.kill(a)


def test_streaming_requires_generator(stream_cluster):
    @ray_tpu.remote
    def not_gen():
        return 1

    with pytest.raises(TypeError, match="generator"):
        not_gen.options(num_returns="streaming").remote()


def test_streaming_many_items(stream_cluster):
    @ray_tpu.remote
    def wide():
        yield from range(200)

    total = sum(ray_tpu.get(r, timeout=120)
                for r in wide.options(num_returns="streaming").remote())
    assert total == sum(range(200))
