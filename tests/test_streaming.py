"""Streaming-generator task tests (reference strategy:
python/ray/tests/test_streaming_generator*.py)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def stream_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_streaming_basic(stream_cluster):
    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref, timeout=60)
           for ref in gen.options(num_returns="streaming").remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_streaming_incremental_delivery(stream_cluster):
    @ray_tpu.remote
    def slow_gen():
        for i in range(4):
            yield i
            time.sleep(0.4)

    g = slow_gen.options(num_returns="streaming").remote()
    t0 = time.time()
    first = ray_tpu.get(next(iter(g)), timeout=60)
    first_latency = time.time() - t0
    assert first == 0
    # The first item must arrive well before the task finishes (~1.6s).
    assert first_latency < 1.2, first_latency
    rest = [ray_tpu.get(r, timeout=60) for r in g]
    assert rest == [1, 2, 3]


def test_streaming_large_items_through_store(stream_cluster):
    @ray_tpu.remote
    def big_gen():
        for i in range(3):
            yield np.full((300_000,), i, dtype=np.float64)  # > inline

    vals = [ray_tpu.get(r, timeout=120)
            for r in big_gen.options(num_returns="streaming").remote()]
    assert [v[0] for v in vals] == [0.0, 1.0, 2.0]
    assert all(v.shape == (300_000,) for v in vals)


def test_streaming_error_mid_stream(stream_cluster):
    @ray_tpu.remote
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("stream exploded")

    g = bad_gen.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(g), timeout=60) == 1
    assert ray_tpu.get(next(g), timeout=60) == 2
    with pytest.raises(Exception, match="stream exploded"):
        next(g)  # the failure surfaces at end-of-stream


def test_streaming_pre_generator_failure_closes_stream(stream_cluster):
    @ray_tpu.remote
    def gen_bad_env():
        yield 1

    g = (gen_bad_env
         .options(num_returns="streaming",
                  runtime_env={"pip": ["requests"]})
         .remote())
    # pip envs are supported now; this one fails during SETUP (the
    # offline host can't resolve pypi), which is exactly the
    # pre-generator failure the test needs.
    with pytest.raises(Exception, match="runtime.?env"):
        next(g)  # setup error closes the stream instead of hanging


def test_streaming_on_sync_actor_method(stream_cluster):
    class A:
        def gen(self, n):
            for i in range(n):
                yield i * 3

    a = ray_tpu.remote(A).options(num_cpus=0.1).remote()
    g = a.gen.options(num_returns="streaming").remote(4)
    out = [ray_tpu.get(r, timeout=60) for r in g]
    assert out == [0, 3, 6, 9]
    ray_tpu.kill(a)


def test_streaming_on_async_actor_method(stream_cluster):
    class A:
        async def agen(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i + 100

    a = ray_tpu.remote(A).options(num_cpus=0.1).remote()
    g = a.agen.options(num_returns="streaming").remote(4)
    out = [ray_tpu.get(r, timeout=60) for r in g]
    assert out == [100, 101, 102, 103]
    ray_tpu.kill(a)


def test_streaming_actor_method_not_a_generator(stream_cluster):
    class A:
        def plain(self):
            return 42

    a = ray_tpu.remote(A).options(num_cpus=0.1).remote()
    g = a.plain.options(num_returns="streaming").remote()
    with pytest.raises(Exception, match="generator"):
        next(g)
    ray_tpu.kill(a)


def test_streaming_backpressure_bounds_producer(stream_cluster):
    """max_queued_stream_chunks pauses the generator body once that
    many chunks are produced-but-unread (credit-based flow control)."""

    class Producer:
        def __init__(self):
            self.produced = 0

        async def gen(self, n):
            for i in range(n):
                self.produced += 1
                yield i

        async def count(self):
            return self.produced

    a = ray_tpu.remote(Producer).options(num_cpus=0.1).remote()
    g = a.gen.options(num_returns="streaming",
                      max_queued_stream_chunks=3).remote(60)
    first = ray_tpu.get(next(g), timeout=60)
    time.sleep(1.0)
    produced = ray_tpu.get(a.count.remote(), timeout=60)
    # 1 read + window of 3 + one chunk mid-flight.
    assert produced <= 5, produced
    rest = [ray_tpu.get(r, timeout=60) for r in g]
    assert [first] + rest == list(range(60))
    ray_tpu.kill(a)


def test_streaming_consumer_drop_cancels_actor_stream(stream_cluster):
    """Closing the generator propagates cancellation over the actor RPC
    lane: the replica-side generator actually stops yielding."""

    class Infinite:
        def __init__(self):
            self.n = 0

        async def gen(self):
            while True:
                self.n += 1
                yield self.n

        async def count(self):
            return self.n

    a = ray_tpu.remote(Infinite).options(num_cpus=0.1).remote()
    g = a.gen.options(num_returns="streaming",
                      max_queued_stream_chunks=8).remote()
    ray_tpu.get(next(g), timeout=60)
    g.close()
    time.sleep(1.0)
    n1 = ray_tpu.get(a.count.remote(), timeout=60)
    time.sleep(0.5)
    n2 = ray_tpu.get(a.count.remote(), timeout=60)
    assert n2 == n1, f"stream kept producing after close: {n1} -> {n2}"
    ray_tpu.kill(a)


def test_streaming_async_iteration(stream_cluster):
    """ObjectRefGenerator is async-iterable (the serve proxy's path)."""
    import asyncio

    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i * 7

    async def consume():
        g = gen.options(num_returns="streaming").remote(5)
        out = []
        async for ref in g:
            out.append(ray_tpu.get(ref, timeout=60))
        return out

    assert asyncio.run(consume()) == [0, 7, 14, 21, 28]


def test_streaming_dropped_generator_cancels_producer(stream_cluster):
    """Dropping the generator WITHOUT close() still cancels the
    producer: the owner's stream registry holds it weakly, so
    abandonment triggers __del__ -> close -> cancel."""
    import gc

    class Infinite:
        def __init__(self):
            self.n = 0

        async def gen(self):
            while True:
                self.n += 1
                yield self.n

        async def count(self):
            return self.n

    a = ray_tpu.remote(Infinite).options(num_cpus=0.1).remote()
    g = a.gen.options(num_returns="streaming",
                      max_queued_stream_chunks=8).remote()
    ray_tpu.get(next(g), timeout=60)
    del g  # no close(); the drop itself is the cancel signal
    gc.collect()
    time.sleep(1.0)
    n1 = ray_tpu.get(a.count.remote(), timeout=60)
    time.sleep(0.5)
    n2 = ray_tpu.get(a.count.remote(), timeout=60)
    assert n2 == n1, f"producer survived generator drop: {n1} -> {n2}"
    ray_tpu.kill(a)


def test_streaming_close_wakes_blocked_consumer(stream_cluster):
    """close() from another thread ends iteration for a consumer
    blocked in __next__ (the gRPC cancel-callback shape) instead of
    leaving it waiting forever."""
    import threading

    @ray_tpu.remote
    def trickle():
        yield 1
        time.sleep(30)  # consumer will block waiting for item 2
        yield 2

    g = trickle.options(num_returns="streaming").remote()
    ray_tpu.get(next(g), timeout=60)
    result = {}

    def consume():
        try:
            next(g)
            result["outcome"] = "item"
        except StopIteration:
            result["outcome"] = "stopped"
        except Exception as e:  # noqa: BLE001
            result["outcome"] = f"error: {e}"

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.3)  # let the consumer block in __next__
    g.close()
    t.join(timeout=10)
    assert not t.is_alive(), "consumer still blocked after close()"
    assert result["outcome"] == "stopped", result


def test_streaming_iterator_timeout_message(stream_cluster):
    """next_ready's timeout raises the documented error."""

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        yield 1

    g = slow.options(num_returns="streaming").remote()
    with pytest.raises(Exception, match="stream item not ready in time"):
        g.next_ready(timeout=0.2)
    g.close()


def test_streaming_abandoned_stream_releases_queued_items(stream_cluster):
    """Dropping a generator with queued unread items deregisters the
    stream; late stream_items for it are refused (no owner-side leak)."""
    from ray_tpu import api as _api

    @ray_tpu.remote
    def wide():
        yield from range(50)

    g = wide.options(num_returns="streaming").remote()
    ray_tpu.get(next(g), timeout=60)
    cw = _api._require_worker()
    task_id = g._task_id
    assert task_id in cw._streams
    g.close()
    assert task_id not in cw._streams
    # h_stream_item after the drop must not re-register anything.
    deadline = time.time() + 5
    while task_id in cw._streams and time.time() < deadline:
        time.sleep(0.05)
    assert task_id not in cw._streams


def test_streaming_requires_generator(stream_cluster):
    @ray_tpu.remote
    def not_gen():
        return 1

    with pytest.raises(TypeError, match="generator"):
        not_gen.options(num_returns="streaming").remote()


def test_streaming_many_items(stream_cluster):
    @ray_tpu.remote
    def wide():
        yield from range(200)

    total = sum(ray_tpu.get(r, timeout=120)
                for r in wide.options(num_returns="streaming").remote())
    assert total == sum(range(200))
