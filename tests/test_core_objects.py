"""Object store tests (reference model: python/ray/tests/test_object_store*.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import MemoryStore, ShmStore
from ray_tpu.core.serialization import SerializedObject, deserialize, serialize


def test_put_get_small(ray_start):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref, timeout=30) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_large_numpy(ray_start):
    arr = np.random.rand(512, 512)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref, timeout=30)
    np.testing.assert_array_equal(arr, out)


def test_large_object_task_arg(ray_start):
    arr = np.ones((1024, 1024), dtype=np.float32)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(arr), timeout=60) == 1024 * 1024


def test_large_return(ray_start):
    @ray_tpu.remote
    def big():
        return np.arange(500_000, dtype=np.int64)

    out = ray_tpu.get(big.remote(), timeout=60)
    assert out.shape == (500_000,)
    assert out[-1] == 499_999


def test_put_of_ref_rejected(ray_start):
    ref = ray_tpu.put(1)
    with pytest.raises(TypeError):
        ray_tpu.put(ref)


def test_shared_ref_between_tasks(ray_start):
    data = ray_tpu.put(np.full(300_000, 7.0))

    @ray_tpu.remote
    def first(x):
        return float(x[0])

    refs = [first.remote(data) for _ in range(4)]
    assert ray_tpu.get(refs, timeout=60) == [7.0] * 4


# ---- unit tests (no cluster) ----


def test_serialization_roundtrip():
    value = {"x": np.arange(10), "y": "text", "z": (1, 2.5)}
    obj = serialize(value)
    out = deserialize(obj.metadata, obj.inband, obj.buffers)
    np.testing.assert_array_equal(out["x"], value["x"])
    assert out["y"] == "text" and out["z"] == (1, 2.5)


def test_serialization_zero_copy_numpy():
    arr = np.arange(100_000, dtype=np.float64)
    obj = serialize(arr)
    # The array's memory must be an out-of-band buffer, not in the pickle.
    assert sum(memoryview(b).nbytes for b in obj.buffers) >= arr.nbytes
    assert len(obj.inband) < 10_000


def test_memory_store_waiters():
    store = MemoryStore()
    oid = ObjectID.from_random()
    hits = []
    store.add_waiter(oid, hits.append)
    assert not hits
    obj = SerializedObject(metadata=b"N", inband=b"x", buffers=[])
    store.put(oid, obj)
    assert hits == [obj]
    # Waiter after presence fires immediately.
    store.add_waiter(oid, hits.append)
    assert len(hits) == 2


def test_shm_pack_roundtrip():
    value = np.arange(1000, dtype=np.float32)
    obj = serialize(value)
    packed = ShmStore.pack(obj)
    assert len(packed) == ShmStore.packed_size(obj)


def test_shm_store_eviction():
    store = ShmStore(capacity_bytes=10_000)
    a = ObjectID.from_random()
    store.mark_sealed(a, 6_000)
    # Sealed objects carry the primary-copy pin: they are NOT evictable
    # while their owner may still reference them (overflow spills to
    # disk instead). Only an unpinned object can be evicted.
    store.unpin(a)
    b = ObjectID.from_random()
    store.mark_sealed(b, 6_000)  # evicts a (unpinned)
    assert store.used_bytes() <= 10_000
    assert store.contains(b)
    assert not store.contains(a)


def test_shm_store_pin_blocks_eviction():
    store = ShmStore(capacity_bytes=10_000)
    a = ObjectID.from_random()
    store.mark_sealed(a, 6_000)
    store.pin(a)
    b = ObjectID.from_random()
    store.mark_sealed(b, 6_000)  # cannot evict a; over-capacity tolerated
    assert store.contains(a)


def test_main_module_class_arg_roundtrips(ray_start):
    """A class living at driver __main__ must serialize BY VALUE: the C
    pickler serializes it by reference ('__main__.Cfg'), which a worker
    (whose __main__ is worker_main) cannot resolve. serialize() detects
    the __main__ reference and reroutes to cloudpickle (r5 advisor)."""
    import __main__ as main_mod

    class Cfg:
        def __init__(self):
            self.v = 41

    Cfg.__module__ = "__main__"
    Cfg.__qualname__ = "Cfg"
    main_mod.Cfg = Cfg  # simulate a script-level definition
    try:
        @ray_tpu.remote
        def probe(c):
            return c.v + 1

        assert ray_tpu.get(probe.remote(Cfg()), timeout=120) == 42
    finally:
        del main_mod.Cfg
