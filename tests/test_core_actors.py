"""Actor tests (reference test model: python/ray/tests/test_actor.py)."""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.value = start

    def incr(self, by=1):
        self.value += by
        return self.value

    def get(self):
        return self.value


def test_actor_basic(ray_start):
    c = Counter.remote(5)
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 6
    assert ray_tpu.get(c.incr.remote(4), timeout=30) == 10
    assert ray_tpu.get(c.get.remote(), timeout=30) == 10


def test_actor_ordering(ray_start):
    c = Counter.remote(0)
    refs = [c.incr.remote() for _ in range(20)]
    # Sequential execution per submitter: results must be 1..20 in order.
    assert ray_tpu.get(refs, timeout=60) == list(range(1, 21))


def test_actor_init_error(ray_start):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise ValueError("bad init")

        def ping(self):
            return "pong"

    b = Broken.remote()
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(b.ping.remote(), timeout=60)


def test_actor_method_error(ray_start):
    @ray_tpu.remote
    class Flaky:
        def boom(self):
            raise KeyError("nope")

    f = Flaky.remote()
    with pytest.raises(exc.TaskError) as info:
        ray_tpu.get(f.boom.remote(), timeout=60)
    assert info.value.cause_cls_name == "KeyError"


def test_kill_actor(ray_start):
    c = Counter.remote(0)
    ray_tpu.get(c.get.remote(), timeout=60)
    ray_tpu.kill(c)
    time.sleep(0.5)
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(c.get.remote(), timeout=30)


def test_named_actor(ray_start):
    c = Counter.options(name="counter-named").remote(7)
    ray_tpu.get(c.get.remote(), timeout=60)
    h = ray_tpu.get_actor("counter-named")
    assert ray_tpu.get(h.get.remote(), timeout=30) == 7
    with pytest.raises(ValueError):
        ray_tpu.get_actor("no-such-actor")


def test_get_if_exists(ray_start):
    a = Counter.options(name="gie", get_if_exists=True).remote(1)
    ray_tpu.get(a.get.remote(), timeout=60)
    b = Counter.options(name="gie", get_if_exists=True).remote(99)
    # Second create attaches to the first actor.
    assert ray_tpu.get(b.get.remote(), timeout=30) == 1


def test_actor_restart(ray_start):
    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def pid(self):
            import os

            return os.getpid()

        def crash(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    pid1 = ray_tpu.get(p.pid.remote(), timeout=60)
    p.crash.remote()
    time.sleep(3)
    pid2 = ray_tpu.get(p.pid.remote(), timeout=60)
    assert pid1 != pid2


def test_actor_handle_passing(ray_start):
    c = Counter.remote(100)
    ray_tpu.get(c.get.remote(), timeout=60)

    @ray_tpu.remote
    def incr_remote(handle):
        return ray_tpu.get(handle.incr.remote(), timeout=30)

    assert ray_tpu.get(incr_remote.remote(c), timeout=60) == 101
    assert ray_tpu.get(c.get.remote(), timeout=30) == 101


def test_async_actor(ray_start):
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.05)
            return x * 2

    a = AsyncWorker.remote()
    ray_tpu.get(a.work.remote(0), timeout=60)  # wait for actor start
    t0 = time.monotonic()
    refs = [a.work.remote(i) for i in range(8)]
    results = ray_tpu.get(refs, timeout=60)
    elapsed = time.monotonic() - t0
    assert results == [i * 2 for i in range(8)]
    # Concurrent execution: 8 × 50ms sleeps must overlap.
    assert elapsed < 2.0


def test_threaded_actor_concurrency(ray_start):
    @ray_tpu.remote(max_concurrency=4)
    class Blocker:
        def block(self, t):
            time.sleep(t)
            return t

    b = Blocker.remote()
    ray_tpu.get(b.block.remote(0), timeout=60)  # wait for actor start
    t0 = time.monotonic()
    refs = [b.block.remote(0.5) for _ in range(4)]
    ray_tpu.get(refs, timeout=60)
    assert time.monotonic() - t0 < 1.9


def test_actor_graceful_exit(ray_start):
    @ray_tpu.remote
    class Quitter:
        def quit(self):
            ray_tpu.actor_exit()

        def ping(self):
            return "pong"

    q = Quitter.remote()
    assert ray_tpu.get(q.ping.remote(), timeout=60) == "pong"
    q.quit.remote()
    time.sleep(1.0)
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(q.ping.remote(), timeout=30)
