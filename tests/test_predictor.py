"""Batch-inference predictor tests (reference strategy:
python/ray/train/tests/test_torch_predictor.py + batch inference
examples)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def pred_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _linear_apply(params, x):
    return x @ params["w"] + params["b"]


def test_predictor_predict(pred_cluster):
    from ray_tpu.train import JaxPredictor

    params = {"w": np.array([[2.0], [3.0]], np.float32),
              "b": np.array([1.0], np.float32)}
    p = JaxPredictor(_linear_apply, params)
    batch = np.array([[1.0, 1.0], [2.0, 0.0]], np.float32)
    out = p.predict(batch)
    np.testing.assert_allclose(out["predictions"],
                               [[6.0], [5.0]], rtol=1e-6)


def test_predictor_from_checkpoint_and_dataset(pred_cluster, tmp_path):
    from ray_tpu import data as rd
    from ray_tpu.train import Checkpoint, predict_dataset

    params = {"w": np.array([[2.0], [3.0]], np.float32),
              "b": np.array([1.0], np.float32)}
    ckpt = Checkpoint.from_pytree(params, str(tmp_path / "ck"),
                                  shard_rank=0)

    n = 37  # deliberately ragged vs batch_size=8
    ds = rd.from_numpy(
        np.stack([np.arange(n, dtype=np.float32),
                  np.ones(n, dtype=np.float32)], axis=1))
    preds = predict_dataset(ds, checkpoint=ckpt,
                            apply_fn=_linear_apply,
                            batch_size=8, concurrency=2)
    rows = preds.take_all()
    assert len(rows) == n
    got = sorted(float(r["predictions"][0]) for r in rows)
    expect = sorted(2.0 * i + 3.0 + 1.0 for i in range(n))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_from_checkpoint_rejects_multi_shard(pred_cluster, tmp_path):
    from ray_tpu.train import Checkpoint, JaxPredictor

    params = {"w": np.ones((2, 1), np.float32)}
    Checkpoint.from_pytree(params, str(tmp_path / "mck"), shard_rank=0)
    ckpt = Checkpoint.from_pytree(params, str(tmp_path / "mck"),
                                  shard_rank=1)
    with pytest.raises(ValueError, match="shards"):
        JaxPredictor.from_checkpoint(ckpt, _linear_apply)
