"""Concurrency lint plane (ray_tpu/tools/analysis): fixture snippets
that must trip each checker, clean snippets that must not, the pragma
grammar, and — the tier-1 gate — the full suite over ``ray_tpu/``
against the ratcheted baseline (new violations fail; fixed violations
must be banked so the ratchet only tightens)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.tools.analysis import runner
from ray_tpu.tools.analysis.common import collect_pragmas, suppressed


def _lint_source(tmp_path, source, name="mod.py", config_source=""):
    (tmp_path / name).write_text(textwrap.dedent(source))
    return runner.run_lint(root=str(tmp_path),
                           config_source=config_source)


def _details(violations, check=None):
    return [v.detail for v in violations
            if check is None or v.check == check]


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------

def test_sleep_under_lock_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        import time, threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def refresh(self):
                with self._lock:
                    time.sleep(0.5)
        """)
    (d,) = _details(vs, "lock-discipline")
    assert "time.sleep" in d and "self._lock" in d


def test_unbounded_get_and_result_under_lock_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        class W:
            def drain(self):
                with self._lock:
                    item = self.queue.get()
                    out = fut.result()
        """)
    ds = _details(vs, "lock-discipline")
    assert any(".get() without timeout" in d for d in ds)
    assert any(".result() without timeout" in d for d in ds)


def test_bounded_calls_under_lock_clean(tmp_path):
    vs = _lint_source(tmp_path, """
        class W:
            def drain(self):
                with self._lock:
                    item = self.queue.get(timeout=1.0)
                    out = fut.result(timeout=5.0)
                    meta = self.table.get("key")
        """)
    assert not _details(vs, "lock-discipline")


def test_lock_order_cycle_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        def forward():
            with lock_a:
                with lock_b:
                    pass

        def backward():
            with lock_b:
                with lock_a:
                    pass
        """)
    (d,) = _details(vs, "lock-discipline")
    assert d.startswith("lock-order-cycle:")
    assert "lock_a" in d and "lock_b" in d


def test_consistent_lock_order_clean(tmp_path):
    vs = _lint_source(tmp_path, """
        def f():
            with lock_a:
                with lock_b:
                    pass

        def g():
            with lock_a:
                with lock_b:
                    pass
        """)
    assert not _details(vs, "lock-discipline")


def test_nested_def_resets_held_locks(tmp_path):
    # The callback body runs at call time, not while the lock is held.
    vs = _lint_source(tmp_path, """
        import time

        def f(self):
            with self._lock:
                def cb():
                    time.sleep(1.0)
                self.defer(cb)
        """)
    assert not _details(vs, "lock-discipline")


def test_blocking_pragma_suppresses_with_reason(tmp_path):
    vs = _lint_source(tmp_path, """
        import time

        def f(self):
            with self._lock:
                # lint: allow-blocking(startup only; nothing contends yet)
                time.sleep(0.1)
        """)
    assert not _details(vs, "lock-discipline")


# ---------------------------------------------------------------------------
# async hygiene
# ---------------------------------------------------------------------------

def test_blocking_in_async_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        import time, subprocess

        async def handler(self):
            time.sleep(1.0)
            subprocess.run(["ls"])
            item = self.queue.get()
        """)
    ds = _details(vs, "async-hygiene")
    assert any("time.sleep" in d for d in ds)
    assert any("subprocess.run" in d for d in ds)
    assert any(".get() without timeout" in d for d in ds)


def test_awaited_and_wrapped_calls_clean(tmp_path):
    vs = _lint_source(tmp_path, """
        import asyncio

        async def handler(self):
            await asyncio.sleep(1.0)
            item = await self.queue.get()
            more = await asyncio.wait_for(self.queue.get(), 5.0)
            await asyncio.wait_for(ev.wait(), timeout=1.0)
        """)
    assert not _details(vs, "async-hygiene")


def test_sync_def_nested_in_async_clean(tmp_path):
    vs = _lint_source(tmp_path, """
        import time

        async def handler(self):
            def work():
                time.sleep(1.0)
            await loop.run_in_executor(None, work)
        """)
    assert not _details(vs, "async-hygiene")


# ---------------------------------------------------------------------------
# silent-except audit
# ---------------------------------------------------------------------------

def test_silent_except_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        def f():
            try:
                g()
            except Exception:
                pass
        """)
    (d,) = _details(vs, "silent-except")
    assert d == "silent-except: Exception"


def test_silent_except_pragma_with_reason_suppresses(tmp_path):
    vs = _lint_source(tmp_path, """
        def f():
            try:
                g()
            except Exception:  # lint: allow-silent(best-effort kill)
                pass
        """)
    assert not _details(vs, "silent-except")


def test_reasonless_pragma_does_not_suppress(tmp_path):
    vs = _lint_source(tmp_path, """
        def f():
            try:
                g()
            except Exception:  # lint: allow-silent()
                pass
        """)
    assert _details(vs, "silent-except")


def test_handler_with_real_action_clean(tmp_path):
    vs = _lint_source(tmp_path, """
        def f():
            try:
                g()
            except Exception as e:
                logger.warning("boom: %s", e)
        """)
    assert not _details(vs, "silent-except")


# ---------------------------------------------------------------------------
# config-flag lint
# ---------------------------------------------------------------------------

_CONFIG_FIXTURE = textwrap.dedent("""
    from dataclasses import dataclass

    @dataclass
    class Config:
        used_flag: int = 1
        dead_flag: int = 2
    """)


def test_undeclared_config_read_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        from ray_tpu.core.config import get_config

        def f():
            cfg = get_config()
            return cfg.used_flag + get_config().no_such_flag
        """, config_source=_CONFIG_FIXTURE)
    assert ("undeclared-config-read: no_such_flag"
            in _details(vs, "config-flag"))
    assert not any("used_flag" in d for d in _details(vs, "config-flag"))


def test_unread_config_field_detected(tmp_path):
    vs = _lint_source(tmp_path, """
        from ray_tpu.core.config import get_config

        def f():
            return get_config().used_flag
        """, config_source=_CONFIG_FIXTURE)
    assert ("unread-config-field: dead_flag"
            in _details(vs, "config-flag"))
    assert not any("used_flag" in d for d in _details(vs, "config-flag"))


def test_config_annotated_param_tracked(tmp_path):
    vs = _lint_source(tmp_path, """
        from ray_tpu.core.config import Config

        def from_config(config: Config):
            return config.bogus_flag
        """, config_source=_CONFIG_FIXTURE)
    assert ("undeclared-config-read: bogus_flag"
            in _details(vs, "config-flag"))


def test_unrelated_attr_reads_not_config_violations(tmp_path):
    # A foreign object with a .timeout attr must not trip the checker.
    vs = _lint_source(tmp_path, """
        def f(req):
            return req.timeout + req.whatever
        """, config_source=_CONFIG_FIXTURE)
    assert not _details(vs, "config-flag") or all(
        d.startswith("unread-config-field") for d in
        _details(vs, "config-flag"))


# ---------------------------------------------------------------------------
# pragma grammar
# ---------------------------------------------------------------------------

def test_pragma_grammar():
    src = ("x = 1  # lint: allow-silent(reason one)\n"
           "y = 2  # lint: allow-blocking( padded )\n"
           "z = 3  # lint: allow-bogus(nope)\n"
           "w = 4  # lint: allow-silent()\n")
    pragmas = collect_pragmas(src)
    assert pragmas[1]["silent"] == "reason one"
    assert pragmas[2]["blocking"] == "padded"
    assert 3 not in pragmas  # unknown name dropped
    assert 4 not in pragmas  # empty reason dropped
    assert suppressed(pragmas, "silent", 1)
    assert not suppressed(pragmas, "blocking", 1)
    assert suppressed(pragmas, "blocking", 3, 2)


# ---------------------------------------------------------------------------
# ratchet semantics
# ---------------------------------------------------------------------------

def test_ratchet_compare(tmp_path):
    vs = _lint_source(tmp_path, """
        def f():
            try:
                g()
            except Exception:
                pass

        def h():
            try:
                g()
            except Exception:
                pass
        """)
    assert len(vs) == 2
    # Pin both -> clean.
    baseline_path = str(tmp_path / "baseline.json")
    runner.write_baseline(vs, baseline_path)
    baseline = runner.load_baseline(baseline_path)
    new, stale = runner.compare(vs, baseline)
    assert not new and not stale
    # One more violation than pinned -> new.
    new, stale = runner.compare(vs + [vs[0]], baseline)
    assert len(new) == 1 and not stale
    # One fixed -> stale pin must be banked.
    new, stale = runner.compare(vs[:1], baseline)
    assert not new and len(stale) == 1


# ---------------------------------------------------------------------------
# the tier-1 gate: the real package against the real baseline
# ---------------------------------------------------------------------------

def test_package_clean_modulo_baseline():
    violations = runner.run_lint()
    baseline = runner.load_baseline(runner.default_baseline_path())
    assert baseline, "checked-in baseline must exist and be non-empty"
    new, stale = runner.compare(violations, baseline)
    assert not new, (
        "NEW lint violations (fix them, add a # lint: allow-*(<reason>) "
        "pragma, or — for pre-existing debt only — re-pin with "
        "`ray_tpu lint --update-baseline`):\n"
        + "\n".join(v.render() for v in new))
    assert not stale, (
        "violations fixed but still pinned — bank the win with "
        "`ray_tpu lint --update-baseline` so the ratchet tightens:\n"
        + "\n".join(stale))


def test_baseline_only_shrinks_marker():
    """The pinned total is a high-water mark: it must stay under the
    count measured when the lint plane landed (166 on first run, 124
    after this PR's burn-down). Growing it back means new debt was
    baselined instead of fixed."""
    baseline = runner.load_baseline(runner.default_baseline_path())
    total = sum(row.get("count", 0) for row in baseline.values())
    assert total <= 124, (
        f"baseline grew to {total} pinned violations (limit 124) — "
        "new code must ship lint-clean, not enlarge the baseline")


# ---------------------------------------------------------------------------
# CLI (machine consumption)
# ---------------------------------------------------------------------------

def test_cli_lint_json():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "lint", "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    data = json.loads(out.stdout)
    assert data["ok"] is True, (data["new"], data["stale_baseline_keys"])
    assert out.returncode == 0
    assert data["total"] == data["baselined"]
    assert {"check", "path", "line", "context", "detail", "key"} <= set(
        data["violations"][0])
