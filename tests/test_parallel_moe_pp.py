"""Tests for MoE expert parallelism and pipeline parallelism on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel._jax_compat import set_mesh
from ray_tpu.parallel import (
    MeshConfig,
    MoELayer,
    create_mesh,
    local_mesh,
    make_pipeline,
    moe_aux_loss,
    stack_stage_params,
    stage_sharding,
)


def test_moe_forward_shapes_and_aux_loss():
    layer = MoELayer(num_experts=4, ffn_dim=32, k=1, expert_axis=None)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = layer.init(jax.random.PRNGKey(1), x)
    out, state = layer.apply(params, x, mutable=["intermediates"])
    assert out.shape == x.shape
    aux = moe_aux_loss(state["intermediates"])
    # Aux loss ~E*sum(f_i * p_i); uniform routing gives ~1.
    assert float(aux) > 0.1


def test_moe_top2_routes_more_tokens():
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 8))
    l1 = MoELayer(num_experts=4, ffn_dim=16, k=1, expert_axis=None,
                  capacity_factor=4.0)
    l2 = MoELayer(num_experts=4, ffn_dim=16, k=2, expert_axis=None,
                  capacity_factor=4.0)
    p1 = l1.init(jax.random.PRNGKey(1), x)
    out1 = l1.apply(p1, x)
    p2 = l2.init(jax.random.PRNGKey(1), x)
    out2 = l2.apply(p2, x)
    # top-2 output differs from top-1 (second expert contributes).
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_moe_top2_no_cross_token_contamination():
    # A token's output must depend only on itself when capacity is ample:
    # top-1 and top-2 dispatch must not collide on (expert, slot).
    rng = jax.random.PRNGKey(0)
    base = jax.random.normal(rng, (1, 8, 8))
    layer = MoELayer(num_experts=4, ffn_dim=16, k=2, expert_axis=None,
                     capacity_factor=8.0)
    params = layer.init(jax.random.PRNGKey(1), base)
    out_a = layer.apply(params, base)
    # Replace the LAST token only; earlier tokens' outputs must not move.
    changed = base.at[0, -1].set(base[0, -1] + 1.0)
    out_b = layer.apply(params, changed)
    np.testing.assert_allclose(np.asarray(out_a[0, :-1]),
                               np.asarray(out_b[0, :-1]),
                               rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_tokens():
    # All tokens prefer one expert; tiny capacity must drop most.
    x = jnp.ones((1, 16, 8))  # identical tokens -> identical routing
    layer = MoELayer(num_experts=4, ffn_dim=16, k=1, expert_axis=None,
                     capacity_factor=0.25)
    params = layer.init(jax.random.PRNGKey(0), x)
    out = layer.apply(params, x)
    # capacity = ceil(16/4*0.25) = 1 -> only 1 of 16 tokens served.
    served = np.count_nonzero(np.abs(np.asarray(out)).sum(-1) > 1e-9)
    assert served == 1


def test_moe_sharded_matches_unsharded():
    mesh = create_mesh(MeshConfig(data=1, expert=8))
    layer_sh = MoELayer(num_experts=8, ffn_dim=32, k=2,
                        expert_axis="expert")
    layer_ref = MoELayer(num_experts=8, ffn_dim=32, k=2, expert_axis=None)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16))
    params = layer_ref.init(jax.random.PRNGKey(1), x)
    ref = layer_ref.apply(params, x)
    with set_mesh(mesh):
        sh = jax.jit(lambda p, a: layer_sh.apply(p, a))(params, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(sh),
                               rtol=2e-4, atol=2e-4)


def _mlp_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def test_pipeline_matches_sequential():
    n_stages, n_micro, mb, d = 4, 8, 2, 16
    mesh = local_mesh(stage=4)
    rng = np.random.default_rng(0)
    stage_params = [
        {"w": jnp.asarray(rng.normal(size=(d, d)) * 0.3, jnp.float32),
         "b": jnp.asarray(rng.normal(size=(d,)) * 0.1, jnp.float32)}
        for _ in range(n_stages)]
    stacked = stack_stage_params(stage_params)
    x = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)

    pipelined = make_pipeline(_mlp_stage, mesh,
                              num_microbatches=n_micro,
                              axis_name="stage")
    with set_mesh(mesh):
        out = jax.jit(pipelined)(stacked, x)

    expect = x
    for p in stage_params:
        expect = _mlp_stage(p, expect)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_grads_flow():
    n_stages, n_micro, mb, d = 2, 4, 2, 8
    mesh = local_mesh(stage=2)
    rng = np.random.default_rng(1)
    stage_params = [
        {"w": jnp.asarray(rng.normal(size=(d, d)) * 0.3, jnp.float32),
         "b": jnp.zeros((d,), jnp.float32)}
        for _ in range(n_stages)]
    stacked = stack_stage_params(stage_params)
    x = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)
    pipelined = make_pipeline(_mlp_stage, mesh, num_microbatches=n_micro,
                              axis_name="stage")

    def loss(params):
        return jnp.mean(pipelined(params, x) ** 2)

    def ref_loss(params_list):
        h = x
        for p in params_list:
            h = _mlp_stage(p, h)
        return jnp.mean(h ** 2)

    with set_mesh(mesh):
        g = jax.jit(jax.grad(loss))(stacked)
    g_ref = jax.grad(ref_loss)(stage_params)
    for s in range(n_stages):
        np.testing.assert_allclose(
            np.asarray(g["w"][s]), np.asarray(g_ref[s]["w"]),
            rtol=1e-3, atol=1e-4)


def test_pipeline_wrong_microbatch_count_raises():
    mesh = local_mesh(stage=2)
    pipelined = make_pipeline(_mlp_stage, mesh, num_microbatches=4)
    with pytest.raises(ValueError, match="microbatch"):
        pipelined({"w": jnp.zeros((2, 4, 4)), "b": jnp.zeros((2, 4))},
                  jnp.zeros((3, 2, 4)))
