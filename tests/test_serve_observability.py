"""Serve observability: one HTTP request yields a single cross-process
trace (proxy -> router -> replica spans share a trace id) and populates
the serve metric namespace (reference strategy: Serve's request-context
propagation tests + test_metrics.py's serve counters)."""

import json
import os
import time
import urllib.request

import pytest

from ray_tpu.util import tracing

HTTP_PORT = 18731


@pytest.fixture(scope="module")
def traced_serve_cluster(tmp_path_factory):
    # The trace file and enable flag must be in the environment BEFORE
    # init so spawned workers (proxy/replica actors) inherit them.
    trace_file = str(tmp_path_factory.mktemp("traces") / "spans.jsonl")
    os.environ["RAY_TPU_TRACE_FILE"] = trace_file
    tracing.setup_tracing("serve-observability-test")
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield trace_file
    serve.shutdown()
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_TRACE_FILE", None)


def _read_spans(trace_file):
    try:
        with open(trace_file) as f:
            return [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        return []


def test_http_request_single_trace_and_serve_metrics(traced_serve_cluster):
    trace_file = traced_serve_cluster
    from ray_tpu import serve

    @serve.deployment
    class Obs:
        def __call__(self, request):
            return {"ok": True}

    serve.run(Obs.bind(), name="obs_app", route_prefix="/obs",
              http_port=HTTP_PORT)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{HTTP_PORT}/obs", timeout=60) as resp:
        assert json.loads(resp.read()) == {"ok": True}

    # --- one trace across proxy -> router -> replica ---
    deadline = time.time() + 30
    proxy = router = replica = []
    while time.time() < deadline:
        spans = _read_spans(trace_file)
        proxy = [s for s in spans if s["name"].startswith("proxy ")]
        router = [s for s in spans if s["name"].startswith("router ")]
        replica = [s for s in spans if s["name"].startswith("replica ")]
        if proxy and router and replica:
            break
        time.sleep(0.5)
    assert proxy and router and replica, (
        f"missing spans: proxy={len(proxy)} router={len(router)} "
        f"replica={len(replica)}")
    trace_id = proxy[-1]["trace_id"]
    assert any(s["trace_id"] == trace_id for s in router)
    assert any(s["trace_id"] == trace_id for s in replica)

    # --- serve metric namespace populated cluster-wide ---
    from ray_tpu.util import metrics as um

    need = ["ray_tpu_serve_http_requests_total",
            "ray_tpu_serve_http_latency_seconds",
            "ray_tpu_serve_request_latency_seconds",
            "ray_tpu_serve_replica_requests_total"]

    def _served_200(m):
        # Names alone aren't enough: ensure_all() (e.g. the catalog
        # guard) registers every catalog metric with EMPTY values in
        # the driver — wait for the proxy's real 200 sample.
        if not all(n in m for n in need):
            return False
        http = m["ray_tpu_serve_http_requests_total"]["values"]
        return any(dict(tk).get("code") == "200" and v >= 1
                   for tk, v in http.items())

    deadline = time.time() + 45
    merged = {}
    while time.time() < deadline:
        um.flush_metrics()
        merged = um.collect_metrics()
        if _served_200(merged):
            break
        time.sleep(0.5)
    assert _served_200(merged), (
        f"serve metrics incomplete; have "
        f"{ {n: merged.get(n, {}).get('values') for n in need} }")
    # The dashboard's /metrics content renders the serve series.
    text = um.prometheus_text()
    assert "ray_tpu_serve_http_requests_total" in text
    serve.delete("obs_app")
