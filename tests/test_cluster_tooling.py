"""Tests for autoscaler, job submission, CLI, and dashboard
(reference strategy: autoscaler unit tests with fake providers,
dashboard/modules/job/tests, ray CLI smoke tests)."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture()
def tooling_cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_autoscaler_scales_up_for_demand(tooling_cluster):
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        FakeNodeProvider,
        NodeType,
        StandardAutoscaler,
    )

    provider = FakeNodeProvider()
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types=[
            NodeType("cpu_worker", {"CPU": 4.0}, min_workers=0,
                     max_workers=3)],
            idle_timeout_s=3600),
        provider)

    # No demand -> nothing happens.
    report = autoscaler.update()
    assert report["launched"] == []

    # Submit tasks needing more CPUs than the cluster has: the head
    # parks them as pending leases, which the autoscaler must see.
    @ray_tpu.remote
    def hold(sec):
        time.sleep(sec)
        return 1

    refs = [hold.options(num_cpus=2).remote(8) for _ in range(4)]
    time.sleep(1.0)
    report = autoscaler.update()
    assert len(report["launched"]) >= 1
    assert report["pending_demand"] >= 1
    # New capacity lets the queued tasks finish.
    assert ray_tpu.get(refs, timeout=180) == [1, 1, 1, 1]


def test_autoscaler_respects_max_and_min(tooling_cluster):
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        FakeNodeProvider,
        NodeType,
        StandardAutoscaler,
    )

    provider = FakeNodeProvider()
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types=[
            NodeType("w", {"CPU": 1.0}, min_workers=2, max_workers=2)],
            idle_timeout_s=0.1, upscaling_speed=10),
        provider)
    report = autoscaler.update()
    assert len(report["launched"]) == 2  # min_workers floor
    # Idle nodes above min are kept because min_workers=2 == count.
    time.sleep(0.3)
    report = autoscaler.update()
    assert report["terminated"] == []
    assert len(provider.non_terminated_nodes()) == 2


def test_autoscaler_terminates_idle(tooling_cluster):
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        FakeNodeProvider,
        NodeType,
        StandardAutoscaler,
    )

    provider = FakeNodeProvider()
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types=[
            NodeType("w", {"CPU": 1.0}, min_workers=0, max_workers=4)],
            idle_timeout_s=0.2, upscaling_speed=10),
        provider)
    provider.create_node("w", {"CPU": 1.0}, {})
    provider.create_node("w", {"CPU": 1.0}, {})
    autoscaler.update()  # records idle-since
    time.sleep(0.4)
    report = autoscaler.update()
    assert len(report["terminated"]) == 2
    assert provider.non_terminated_nodes() == []


def test_tpu_pod_slice_provider_resources():
    from ray_tpu.autoscaler import TPUPodSliceProvider

    p = TPUPodSliceProvider()
    res = p.slice_resources("v5e-16")
    assert res["TPU"] == 16.0
    assert res["TPU-v5e-16-head"] == 1.0


def test_job_submission(tooling_cluster, tmp_path):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "job_script.py"
    script.write_text(
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import ray_tpu\n"
        "ray_tpu.init(address='auto')\n"
        "@ray_tpu.remote\n"
        "def sq(x):\n"
        "    return x * x\n"
        "print('job result:', ray_tpu.get(sq.remote(7), timeout=60))\n"
        "ray_tpu.shutdown()\n")
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} {script}",
        runtime_env={"env_vars": {"PYTHONPATH": "/root/repo"}})
    status = client.wait_until_finish(job_id, timeout=180)
    logs = client.get_job_logs(job_id)
    assert status == "SUCCEEDED", logs
    assert "job result: 49" in logs
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(tooling_cluster):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(3)'")
    assert client.wait_until_finish(job_id, timeout=120) == "FAILED"


def test_job_stop(tooling_cluster):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'")
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            if client.get_job_status(job_id) == JobStatus.RUNNING:
                break
        except ValueError:
            pass
        time.sleep(0.3)
    assert client.stop_job(job_id)
    assert client.wait_until_finish(job_id, timeout=60) == \
        JobStatus.STOPPED


def test_dashboard_endpoints(tooling_cluster):
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get([noop.remote() for _ in range(3)], timeout=60)
    port = start_dashboard(port=18912)

    def get_json(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return json.loads(r.read())

    status = get_json("/api/cluster_status")
    assert status["cluster_resources"]["CPU"] == 2.0
    assert isinstance(get_json("/api/nodes"), list)
    assert isinstance(get_json("/api/workers"), list)
    assert isinstance(get_json("/api/actors"), list)
    hist = get_json("/api/metrics/history")
    assert hist["enabled"] and isinstance(hist["series"], list)
    alerts = get_json("/api/alerts")
    assert isinstance(alerts.get("rules"), list)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
        assert r.read() == b"success"


def test_cli_status_and_list(tmp_path):
    """CLI attaches to a head started by another process."""
    env = {**os.environ, "PYTHONPATH": "/root/repo",
           "JAX_PLATFORMS": "cpu"}
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--num-cpus", "3",
         "--block"], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            from ray_tpu.api import ADDRESS_FILE

            if os.path.exists(ADDRESS_FILE):
                break
            time.sleep(0.3)
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "status"], env=env,
            capture_output=True, text=True, timeout=90)
        assert "cluster resources" in out.stdout, out.stderr[-500:]
        assert "CPU" in out.stdout
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "list", "nodes"], env=env,
            capture_output=True, text=True, timeout=90)
        assert "node_id" in out.stdout
    finally:
        head.terminate()
        head.wait(timeout=30)


def test_gcp_tpu_provider_drives_gcloud():
    """The concrete GCE slice provider issues create/delete with the
    right accelerator type and a startup script that installs the
    package then joins the cluster (reference: gcp node_provider + TPU
    VM API); its node list reconciles against the cloud."""
    from ray_tpu.autoscaler import GcpTpuPodSliceProvider

    calls = []
    cloud = set()

    def runner(args):
        calls.append(args)
        if args[3] == "create":
            cloud.add(args[4])
        elif args[3] == "delete":
            cloud.discard(args[4])
        elif args[3] == "list":
            return "\n".join(sorted(cloud))
        return ""

    p = GcpTpuPodSliceProvider(
        project="proj", zone="us-central2-b",
        head_address="10.0.0.2:6379",
        setup_commands=["pip install /mnt/ray_tpu.whl"],
        runner=runner)
    sid = p.launch_slice("v5e-16")
    assert sid.startswith("ray-tpu-v5e-16-")
    create = calls[0]
    assert create[:4] == ["compute", "tpus", "tpu-vm", "create"]
    assert "v5litepod-16" in create
    script = create[create.index("--metadata") + 1]
    # Custom delimiter: metadata values with commas (version specs)
    # must not be split into bogus KEY=VALUE pairs by gcloud.
    assert script.startswith("^:::^startup-script=")
    assert "pip install /mnt/ray_tpu.whl" in script
    assert "--head-host 10.0.0.2" in script
    assert "--head-port 6379" in script
    nodes = p.non_terminated_nodes()
    assert nodes and nodes[0]["node_type"] == "v5e-16"
    p.release_slice(sid)
    assert any(c[:4] == ["compute", "tpus", "tpu-vm", "delete"]
               for c in calls)
    p._listed_at = 0.0  # expire the TTL cache
    assert p.non_terminated_nodes() == []

    # Orphan adoption: a slice in the cloud but not in memory (process
    # restarted) is adopted, not leaked.
    cloud.add("ray-tpu-v4-8-deadbeef")
    p._listed_at = 0.0
    adopted = p.non_terminated_nodes()
    assert adopted and adopted[0]["node_type"] == "v4-8"

    import pytest

    with pytest.raises(ValueError):
        p.launch_slice("v9-999")
    # Accelerator names derive from the single TOPOLOGIES table.
    for topo in GcpTpuPodSliceProvider.TOPOLOGIES:
        assert GcpTpuPodSliceProvider.accelerator_type(topo)


def test_autoscaler_v2_declarative_reconcile():
    """v2 instance manager (reference: autoscaler/v2 instance_manager +
    reconciler): declarative counts, explicit lifecycles, provider
    adoption and vanish detection."""
    from ray_tpu.autoscaler.v2 import (
        ClusterSpec,
        InstanceManager,
        NodeTypeSpec,
        RUNNING,
        TERMINATED,
    )

    class FakeProvider:
        def __init__(self):
            self.nodes = {}
            self.counter = 0

        def create_node(self, node_type, resources, labels):
            self.counter += 1
            pid = f"n{self.counter}"
            self.nodes[pid] = {"provider_node_id": pid,
                               "node_type": node_type}
            return pid

        def terminate_node(self, pid):
            self.nodes.pop(pid, None)

        def non_terminated_nodes(self):
            return list(self.nodes.values())

    provider = FakeProvider()
    spec = ClusterSpec(node_types={
        "v5e-16": NodeTypeSpec("v5e-16", min_nodes=1, max_nodes=4,
                               resources={"TPU": 16.0}),
    })
    im = InstanceManager(spec, provider)

    # min_nodes drives the first launch with no explicit target.
    out = im.reconcile()
    assert out["launched"] == {"v5e-16": 1}
    assert len(provider.nodes) == 1

    # Declarative scale-up, clamped by max.
    im.scale("v5e-16", 3)
    im.reconcile()
    assert len(provider.nodes) == 3
    im.scale("v5e-16", 99)
    im.reconcile()
    assert len(provider.nodes) == 4  # max_nodes

    # Scale-down terminates newest-first down to the target.
    im.scale("v5e-16", 1)
    im.reconcile()
    assert len(provider.nodes) == 1
    status = im.cluster_status()
    assert status["by_status"][RUNNING] == 1
    assert status["by_status"][TERMINATED] >= 3

    # A vanished node (preemption) is relaunched toward the target.
    provider.nodes.clear()
    im.reconcile()   # detects vanish, queues + launches replacement
    assert len(provider.nodes) == 1

    # Adoption: a provider node created outside the manager is tracked.
    provider.create_node("v5e-16", {}, {})
    im._sync_with_provider()
    running = [i for i in im.instances.values() if i.status == RUNNING]
    assert len(running) == 2


def test_monitor_scales_up_and_down(tooling_cluster):
    """VERDICT r4 #2: a RUNNING loop (not a library call) scales a
    FakeNodeProvider cluster up for pending demand and back down when
    idle (reference: autoscaler/_private/monitor.py:126,360)."""
    from ray_tpu.autoscaler import AutoscalerConfig, FakeNodeProvider, NodeType
    from ray_tpu.autoscaler.monitor import Monitor
    from ray_tpu.util import state as ust

    provider = FakeNodeProvider()
    config = AutoscalerConfig(
        node_types=[NodeType("cpu_worker", {"CPU": 2.0}, min_workers=0,
                             max_workers=3)],
        idle_timeout_s=1.0, upscaling_speed=10)
    monitor = Monitor(
        config, provider,
        load_fn=lambda: ust._call("get_load"),
        interval_s=0.25, launch_mode="async")
    monitor.start()
    try:
        @ray_tpu.remote
        def hold(sec):
            time.sleep(sec)
            return 1

        # Demand beyond the base cluster: 3 two-CPU holds on a 2-CPU
        # head. The monitor must launch fake nodes while demand is
        # pending (the head alone could only run them sequentially).
        refs = [hold.options(num_cpus=2).remote(3) for _ in range(3)]
        deadline = time.time() + 120
        while time.time() < deadline:
            if provider.non_terminated_nodes():
                break
            time.sleep(0.25)
        assert len(provider.non_terminated_nodes()) >= 1
        assert ray_tpu.get(refs, timeout=240) == [1, 1, 1]
        status = monitor.status()
        assert status["running"]
        assert status["last_summary"]["tick"] >= 1
        # Idle: everything above min_workers=0 drains after the timeout.
        deadline = time.time() + 60
        while time.time() < deadline:
            if not provider.non_terminated_nodes():
                break
            time.sleep(0.5)
        assert provider.non_terminated_nodes() == []
        # Status surfaces over RPC for the CLI/dashboard.
        over_rpc = ust._call("autoscaler_status")
        assert over_rpc == {"enabled": False}  # monitor ran in-driver
    finally:
        monitor.stop()


def test_head_embedded_monitor_flag(tmp_path, monkeypatch):
    """RAY_TPU_AUTOSCALER=1 + config file: the HEAD process runs the
    monitor; status is served over the autoscaler_status RPC the CLI
    and dashboard consume."""
    cfg = {
        "node_types": [{"name": "cpu_worker",
                        "resources": {"CPU": 2.0},
                        "min_workers": 0, "max_workers": 2}],
        "idle_timeout_s": 1.0,
        "interval_s": 0.25,
        "provider": {"type": "fake"},
    }
    path = tmp_path / "cluster.json"
    path.write_text(json.dumps(cfg))
    monkeypatch.setenv("RAY_TPU_AUTOSCALER", "1")
    monkeypatch.setenv("RAY_TPU_AUTOSCALER_CONFIG", str(path))
    ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        from ray_tpu.util import state as ust

        deadline = time.time() + 30
        status = {}
        while time.time() < deadline:
            status = ust._call("autoscaler_status")
            if status.get("enabled") and \
                    status.get("last_summary", {}).get("tick", 0) >= 1:
                break
            time.sleep(0.25)
        assert status.get("enabled"), status
        assert status["running"]
        assert status["last_summary"]["tick"] >= 1
    finally:
        ray_tpu.shutdown()
