"""Memory watchdog: pressure kills a worker, retries recover the task,
the node survives (reference: memory_monitor.h:52 +
worker_killing_policy_retriable_fifo.cc; release test
test_memory_pressure.py's kill-and-retry assertions)."""

import os

import numpy as np
from ray_tpu.core import memory_monitor as mm


def test_node_memory_reads_something():
    used, limit = mm.node_memory()
    assert used > 0
    assert limit >= used


def test_limit_override(monkeypatch):
    monkeypatch.setenv("RAY_TPU_MEMORY_LIMIT_BYTES", "123456789")
    _, limit = mm.node_memory()
    assert limit == 123456789


def test_process_rss_self():
    rss = mm.process_rss(os.getpid())
    assert rss > 10 << 20  # a python interpreter is >10MB


def test_pick_victim_policy():
    c = [
        mm.VictimCandidate("old-nonretr", 1, False, False, 10.0),
        mm.VictimCandidate("old-retr", 2, True, False, 10.0),
        mm.VictimCandidate("new-retr", 3, True, False, 20.0),
        mm.VictimCandidate("actor", 4, True, True, 30.0),
    ]
    assert mm.pick_victim(c).worker_id_hex == "new-retr"
    # No retriable tasks: non-retriable tasks go before actors.
    c2 = [
        mm.VictimCandidate("actor", 4, True, True, 30.0),
        mm.VictimCandidate("old-nonretr", 1, False, False, 10.0),
    ]
    assert mm.pick_victim(c2).worker_id_hex == "old-nonretr"
    assert mm.pick_victim([]) is None
    # pid<=0 (agent-managed placeholder) is never a victim.
    assert mm.pick_victim(
        [mm.VictimCandidate("remote", -1, True, False, 1.0)]) is None


def test_monitor_kills_once_per_interval(monkeypatch):
    kills = []
    monitor = mm.MemoryMonitor(
        threshold=0.9,
        candidates=lambda: [mm.VictimCandidate("w1", os.getpid(), True,
                                               False, 1.0)],
        kill=lambda v, reason: kills.append((v.worker_id_hex, reason)),
        min_kill_interval_s=60.0,
    )
    monkeypatch.setattr(mm, "node_memory", lambda: (95, 100))
    assert monitor.maybe_kill() == "w1"
    assert monitor.maybe_kill() is None  # within the kill interval
    assert len(kills) == 1
    assert "memory monitor" in kills[0][1]
    # Below threshold: no kill even after the interval.
    monitor._last_kill = 0.0
    monkeypatch.setattr(mm, "node_memory", lambda: (50, 100))
    assert monitor.maybe_kill() is None


def test_oom_task_killed_and_retried(monkeypatch):
    """Chaos: a task that allocates far past the (narrowed) node limit
    is killed by the monitor; its retry — with the pressure gone — runs
    elsewhere and completes; the cluster stays usable."""
    import ray_tpu

    used, _ = mm.node_memory()
    # Narrow the limit so the allocating worker crosses it long before
    # the machine actually hurts: headroom of ~400MB over current use.
    # Workers and the in-process head read the env at poll time.
    monkeypatch.setenv("RAY_TPU_MEMORY_LIMIT_BYTES",
                       str(used + (400 << 20)))
    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:

        @ray_tpu.remote(max_retries=3)
        def hog(flag_path):
            # First attempt allocates ~1.2GB and parks, tripping the
            # monitor; retries (flag file exists) return immediately.
            if os.path.exists(flag_path):
                return "recovered"
            with open(flag_path, "w") as f:
                f.write("1")
            import time as _t

            blocks = []
            for _ in range(120):
                blocks.append(np.ones(10 * 1024 * 1024 // 8))  # 10MB
                _t.sleep(0.02)
            _t.sleep(30)
            return "survived-without-kill"

        flag = os.path.join("/tmp", f"oomflag_{os.getpid()}")
        try:
            out = ray_tpu.get(hog.remote(flag), timeout=180)
        finally:
            try:
                os.remove(flag)
            except OSError:
                pass
        assert out == "recovered"

        # Node survives: plain work still runs.
        @ray_tpu.remote
        def ok():
            return 42

        assert ray_tpu.get(ok.remote(), timeout=60) == 42
    finally:
        ray_tpu.shutdown()
