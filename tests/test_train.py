"""Train orchestration tests (reference strategy:
python/ray/train/tests/test_data_parallel_trainer.py et al.)."""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import CheckpointConfig


# ---------------------------------------------------------------------------
# CheckpointManager unit tests (no cluster)
# ---------------------------------------------------------------------------


def _mk_ckpt(tmp_path, i):
    d = os.path.join(tmp_path, f"c{i}")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "marker"), "w") as f:
        f.write(str(i))
    return Checkpoint(d)


def test_checkpoint_manager_topk(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="acc"))
    cks = [_mk_ckpt(tmp_path, i) for i in range(4)]
    scores = [0.1, 0.9, 0.5, 0.2]
    for c, s in zip(cks, scores):
        mgr.register(c, {"acc": s})
    # Top-2 by score (0.9, 0.5) survive; latest (0.2) retained on top.
    assert mgr.best is cks[1]
    assert mgr.latest is cks[3]
    assert os.path.isdir(cks[1].path)
    assert os.path.isdir(cks[2].path)
    assert os.path.isdir(cks[3].path)
    assert not os.path.isdir(cks[0].path)


def test_checkpoint_manager_min_order(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=1, checkpoint_score_attribute="loss",
        checkpoint_score_order="min"))
    cks = [_mk_ckpt(tmp_path, i) for i in range(3)]
    for c, s in zip(cks, [3.0, 1.0, 2.0]):
        mgr.register(c, {"loss": s})
    # num_to_keep=1 keeps the best; the latest is retained additionally.
    assert mgr.best is cks[1]
    assert os.path.isdir(mgr.best.path)
    assert os.path.isdir(mgr.latest.path)
    assert not os.path.isdir(cks[0].path)


def test_checkpoint_pytree_roundtrip(tmp_path):
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.zeros(3), "step": 7}
    ckpt = Checkpoint.from_pytree(tree, str(tmp_path / "ck"),
                                  user_meta={"note": "hi"})
    out = ckpt.to_pytree()
    np.testing.assert_array_equal(out["w"], tree["w"])
    assert out["step"] == 7
    assert ckpt.user_meta == {"note": "hi"}


# ---------------------------------------------------------------------------
# end-to-end trainer tests
# ---------------------------------------------------------------------------


def test_trainer_streams_reports(ray_start, tmp_path):
    def loop(config):
        ctx = train.get_context()
        for step in range(config["steps"]):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "world": ctx.get_world_size()})

    trainer = train.JaxTrainer(
        loop,
        train_loop_config={"steps": 3},
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="stream",
                                   storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics == {"step": 2, "rank": 0, "world": 2}


def test_trainer_checkpoint_topk_and_result(ray_start, tmp_path):
    def loop(config):
        for step in range(4):
            d = tempfile.mkdtemp()
            ckpt = Checkpoint.from_pytree({"step": step}, d)
            train.report({"step": step, "score": float(step)}, ckpt)

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="ckpt", storage_path=str(tmp_path),
            checkpoint_config=train.CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score")),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    assert result.checkpoint.to_pytree()["step"] == 3
    exp = os.path.join(str(tmp_path), "ckpt")
    kept = sorted(d for d in os.listdir(exp) if d.startswith("checkpoint_"))
    assert len(kept) == 2  # top-K pruning happened on disk


def test_trainer_failure_restart_resumes(ray_start, tmp_path):
    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            if step == 2 and start == 0:
                raise RuntimeError("injected failure at step 2")
            d = tempfile.mkdtemp()
            train.report({"step": step},
                         Checkpoint.from_pytree({"step": step}, d))

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="restart", storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.error is None
    # Steps 0,1 from attempt one; resumed at 2 (from ckpt step 1), then 2,3.
    assert [m["step"] for m in result.metrics_history] == [0, 1, 2, 3]


def test_trainer_failure_exhausted(ray_start, tmp_path):
    def loop(config):
        raise ValueError("always broken")

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="broken",
                                   storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is not None and "always broken" in result.error


def test_trainer_dataset_sharding(ray_start, tmp_path):
    def loop(config):
        shard = train.get_dataset_shard("train")
        train.report({"shard": list(shard)})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": [0, 1, 2, 3, 4, 5]},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["shard"] == [0, 2, 4]  # rank 0 strided shard


def test_trainer_jax_mlp_e2e(ray_start, tmp_path):
    """SURVEY.md §7.2 minimum slice: sharded MLP train loop in a worker
    actor, loss decreasing, sharded-pytree checkpoint reported."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.mlp import MLP
        from ray_tpu.parallel import MeshConfig, create_mesh
        from ray_tpu.train.spmd import make_sharded_train

        mesh = create_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
        model = MLP(features=(16, 4))
        x = jnp.asarray(np.random.RandomState(0).rand(8, 8), jnp.float32)
        y = jnp.asarray(np.arange(8) % 4)
        batch = {"inputs": x, "targets": y}

        def loss_fn(logits, b):
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, b["targets"]).mean()

        init, step_fn, _ = make_sharded_train(
            model, optax.adam(1e-2), mesh, batch, loss_fn,
        )
        state = init(jax.random.PRNGKey(0))
        losses = []
        for i in range(8):
            state, metrics = step_fn(state, batch)
            losses.append(float(metrics["loss"]))
        d = tempfile.mkdtemp()
        ckpt = Checkpoint.from_pytree(
            jax.device_get(state.params), d)
        train.report({"first_loss": losses[0], "last_loss": losses[-1]},
                     ckpt)

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="mlp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["last_loss"] < result.metrics["first_loss"]
    params = result.checkpoint.to_pytree()
    import jax

    assert len(jax.tree.leaves(params)) > 0  # restored non-empty pytree
