"""Train orchestration tests (reference strategy:
python/ray/train/tests/test_data_parallel_trainer.py et al.) +
recovery-semantics coverage: hang detection under the report timeout,
crash-consistent checkpoint commit (COMMIT marker), torn-checkpoint
skip on recovery, elastic shrink to min_workers, and restart under
network fault injection."""

import os
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train.checkpoint import COMMIT_MARKER, Checkpoint
from ray_tpu.train.checkpoint_manager import (
    CheckpointManager,
    TornCheckpointError,
)
from ray_tpu.train.config import CheckpointConfig, FailureConfig


# ---------------------------------------------------------------------------
# CheckpointManager unit tests (no cluster)
# ---------------------------------------------------------------------------


def _mk_ckpt(tmp_path, i):
    d = os.path.join(tmp_path, f"c{i}")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "marker"), "w") as f:
        f.write(str(i))
    return Checkpoint(d)


def test_checkpoint_manager_topk(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="acc"))
    cks = [_mk_ckpt(tmp_path, i) for i in range(4)]
    scores = [0.1, 0.9, 0.5, 0.2]
    for c, s in zip(cks, scores):
        mgr.register(c, {"acc": s})
    # Top-2 by score (0.9, 0.5) survive; latest (0.2) retained on top.
    assert mgr.best is cks[1]
    assert mgr.latest is cks[3]
    assert os.path.isdir(cks[1].path)
    assert os.path.isdir(cks[2].path)
    assert os.path.isdir(cks[3].path)
    assert not os.path.isdir(cks[0].path)


def test_checkpoint_manager_min_order(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=1, checkpoint_score_attribute="loss",
        checkpoint_score_order="min"))
    cks = [_mk_ckpt(tmp_path, i) for i in range(3)]
    for c, s in zip(cks, [3.0, 1.0, 2.0]):
        mgr.register(c, {"loss": s})
    # num_to_keep=1 keeps the best; the latest is retained additionally.
    assert mgr.best is cks[1]
    assert os.path.isdir(mgr.best.path)
    assert os.path.isdir(mgr.latest.path)
    assert not os.path.isdir(cks[0].path)


def test_checkpoint_pytree_roundtrip(tmp_path):
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.zeros(3), "step": 7}
    ckpt = Checkpoint.from_pytree(tree, str(tmp_path / "ck"),
                                  user_meta={"note": "hi"})
    out = ckpt.to_pytree()
    np.testing.assert_array_equal(out["w"], tree["w"])
    assert out["step"] == 7
    assert ckpt.user_meta == {"note": "hi"}


# ---------------------------------------------------------------------------
# crash-consistent checkpoint commit (COMMIT marker)
# ---------------------------------------------------------------------------


def test_checkpoint_commit_marker_and_atomic_writes(tmp_path):
    ckpt = Checkpoint.from_pytree({"step": 3}, str(tmp_path / "ck"))
    # Commit marker written last, records the shard set with sizes.
    info = ckpt.commit_info()
    assert info is not None
    shard = os.path.join(ckpt.path, "shard_0.msgpack")
    assert info["shards"]["shard_0.msgpack"] == os.path.getsize(shard)
    assert info["has_meta"] is True
    assert ckpt.validate_committed() is None
    # Atomic writes leave no temp droppings behind.
    assert not [f for f in os.listdir(ckpt.path) if ".tmp." in f]


def test_checkpoint_torn_detection(tmp_path):
    ckpt = Checkpoint.from_pytree({"w": np.ones(8)}, str(tmp_path / "ck"))
    assert ckpt.validate_committed() is None
    # Truncated shard: size no longer matches the committed record.
    shard = os.path.join(ckpt.path, "shard_0.msgpack")
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) // 2)
    assert "truncated" in ckpt.validate_committed()
    # Missing marker with shards present is torn too (writer crashed
    # before the commit point).
    ckpt2 = Checkpoint.from_pytree({"w": np.ones(8)}, str(tmp_path / "c2"))
    os.remove(os.path.join(ckpt2.path, COMMIT_MARKER))
    assert "COMMIT" in ckpt2.validate_committed()
    # Missing listed shard.
    ckpt3 = Checkpoint.from_pytree({"w": np.ones(8)}, str(tmp_path / "c3"))
    os.remove(os.path.join(ckpt3.path, "shard_0.msgpack"))
    assert "missing shard" in ckpt3.validate_committed()


def test_checkpoint_manager_rejects_torn(tmp_path):
    ckpt = Checkpoint.from_pytree({"w": np.ones(4)}, str(tmp_path / "ck"))
    os.remove(os.path.join(ckpt.path, COMMIT_MARKER))
    mgr = CheckpointManager(CheckpointConfig())
    with pytest.raises(TornCheckpointError):
        mgr.register(ckpt, {})
    assert mgr.latest is None


def _committed_dir(exp_dir, seq, step, score=None):
    path = os.path.join(exp_dir, f"checkpoint_{seq:06d}")
    ckpt = Checkpoint.from_pytree({"step": step}, path)
    metrics = {"step": step}
    if score is not None:
        metrics["score"] = score
    ckpt.commit(extra={"metrics": metrics, "seq": seq})
    return ckpt


def test_checkpoint_manager_recover_from_dir(tmp_path):
    exp = str(tmp_path / "exp")
    os.makedirs(exp)
    _committed_dir(exp, 0, step=0, score=0.1)
    _committed_dir(exp, 1, step=1, score=0.9)
    torn = _committed_dir(exp, 2, step=2, score=0.5)
    shard = os.path.join(torn.path, "shard_0.msgpack")
    with open(shard, "r+b") as f:  # driver crashed mid-write
        f.truncate(3)
    mgr = CheckpointManager(CheckpointConfig(
        checkpoint_score_attribute="score"))
    assert mgr.recover_from_dir(exp) == 2
    # The torn dir is never the resume anchor; scores came from the
    # COMMIT markers.
    assert mgr.latest.to_pytree()["step"] == 1
    assert mgr.best.to_pytree()["step"] == 1
    assert CheckpointManager.next_seq_on_disk(exp) == 3


# ---------------------------------------------------------------------------
# end-to-end trainer tests
# ---------------------------------------------------------------------------


def test_trainer_streams_reports(ray_start, tmp_path):
    def loop(config):
        ctx = train.get_context()
        for step in range(config["steps"]):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "world": ctx.get_world_size()})

    trainer = train.JaxTrainer(
        loop,
        train_loop_config={"steps": 3},
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="stream",
                                   storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics == {"step": 2, "rank": 0, "world": 2}


def test_trainer_checkpoint_topk_and_result(ray_start, tmp_path):
    def loop(config):
        for step in range(4):
            d = tempfile.mkdtemp()
            ckpt = Checkpoint.from_pytree({"step": step}, d)
            train.report({"step": step, "score": float(step)}, ckpt)

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="ckpt", storage_path=str(tmp_path),
            checkpoint_config=train.CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score")),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    assert result.checkpoint.to_pytree()["step"] == 3
    exp = os.path.join(str(tmp_path), "ckpt")
    kept = sorted(d for d in os.listdir(exp) if d.startswith("checkpoint_"))
    assert len(kept) == 2  # top-K pruning happened on disk


def test_trainer_failure_restart_resumes(ray_start, tmp_path):
    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            if step == 2 and start == 0:
                raise RuntimeError("injected failure at step 2")
            d = tempfile.mkdtemp()
            train.report({"step": step},
                         Checkpoint.from_pytree({"step": step}, d))

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="restart", storage_path=str(tmp_path),
            failure_config=train.FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.error is None
    # Steps 0,1 from attempt one; resumed at 2 (from ckpt step 1), then 2,3.
    assert [m["step"] for m in result.metrics_history] == [0, 1, 2, 3]


def test_trainer_failure_exhausted(ray_start, tmp_path):
    def loop(config):
        raise ValueError("always broken")

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="broken",
                                   storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is not None and "always broken" in result.error


def test_trainer_dataset_sharding(ray_start, tmp_path):
    def loop(config):
        shard = train.get_dataset_shard("train")
        train.report({"shard": list(shard)})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": [0, 1, 2, 3, 4, 5]},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["shard"] == [0, 2, 4]  # rank 0 strided shard


# ---------------------------------------------------------------------------
# recovery semantics (gang health monitor, torn skip, elastic restart)
# ---------------------------------------------------------------------------


def test_hang_detected_under_report_timeout(ray_start, tmp_path):
    """A rank that stops reporting is flagged by the health monitor in
    seconds — NOT after the 600 s report timeout — with rank + step
    attribution."""

    def loop(config):
        ctx = train.get_context()
        for step in range(5):
            if step == 2 and ctx.get_world_rank() == 0:
                time.sleep(60)  # wedged collective / device stand-in
            train.report({"step": step})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(
            name="hang", storage_path=str(tmp_path),
            failure_config=FailureConfig(
                max_failures=0,
                health_check_interval_s=0.25,
                hang_timeout_s=1.5)),
    )
    start = time.monotonic()
    result = trainer.fit()
    elapsed = time.monotonic() - start
    assert result.error is not None
    assert "hung" in result.error and "rank 0" in result.error
    assert elapsed < 30.0, f"hang detection took {elapsed:.1f}s"


def test_hang_attribution_by_step_phase(ray_start, tmp_path):
    """The device step-counter heartbeat separates WHY a rank wedged:
    a stall inside the compile phase, inside the jitted step, and at
    plain python level yield three distinct gang-abort reasons instead
    of one generic hang (live profiling plane)."""

    def make_loop(phase):
        def loop(config):
            for step in range(3):
                if step == 1:
                    if phase is None:
                        time.sleep(60)  # host-side block, no phase
                    else:
                        with train.step_phase(phase):
                            time.sleep(60)  # wedged device stand-in
                train.report({"step": step})
        return loop

    def run(name, loop):
        trainer = train.JaxTrainer(
            loop,
            scaling_config=train.ScalingConfig(num_workers=1),
            run_config=train.RunConfig(
                name=name, storage_path=str(tmp_path),
                failure_config=FailureConfig(
                    max_failures=0,
                    health_check_interval_s=0.25,
                    hang_timeout_s=1.2)),
        )
        result = trainer.fit()
        assert result.error is not None
        return result.error

    err = run("hang-compile", make_loop("compile"))
    assert "hung compiling step 1" in err, err
    assert "compilation stall" in err

    err = run("hang-step", make_loop("step"))
    assert "stalled in jitted step 1" in err, err
    assert "device or collective" in err
    assert "unresponsive" not in err

    err = run("hang-python", make_loop(None))
    assert "hung at python level in step 1" in err, err

    # Each sweep fed the per-rank staleness gauge and the step/phase
    # changes landed as train/step:r<rank> timeline lane markers.
    from ray_tpu.util import telemetry

    gauge = telemetry.metric(
        "ray_tpu_train_step_heartbeat_age_seconds")
    assert any(("rank", "0") in key for key in gauge._values)
    lanes = {ev["cat"] for ev in telemetry.local_timeline_events()}
    assert "train/step:r0" in lanes
    # The stale-heartbeat evidence reached the flight ring.
    from ray_tpu.util import flight_recorder

    stale = [e for e in flight_recorder.snapshot()
             if e["event"] == "step_heartbeat_stale"]
    assert stale and stale[-1]["severity"] == "error"
    assert stale[-1]["tags"]["step"] == 1


def test_instrument_step_phases(ray_start, tmp_path):
    """instrument_step advances the heartbeat host-side around the
    jitted step: first call = compile, later calls = step, and the
    session ends each report back at python level."""
    def loop(config):
        from ray_tpu.train import session as session_mod

        sess = session_mod._get_session()
        observed = []

        def raw_step(x):
            observed.append(sess.step_phase)
            return x + 1

        step_fn = train.instrument_step(raw_step)
        acc = 0
        for step in range(3):
            acc = step_fn(acc)
            train.report({"acc": acc, "observed": list(observed),
                          "phase_after": sess.step_phase})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="instr",
                                   storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["acc"] == 3
    # Phase observed INSIDE the step: compile once, then step.
    assert result.metrics["observed"] == ["compile", "step", "step"]
    # ... and the wrapper restored python level before each report.
    assert result.metrics["phase_after"] == ""


def test_worker_death_detected_and_restart_resumes(ray_start, tmp_path):
    """A dying worker process aborts the gang with death attribution;
    the restart resumes from the latest committed checkpoint."""
    died_marker = str(tmp_path / "died_once")

    def loop(config):
        ctx = train.get_context()
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            if (step == 2 and ctx.get_world_rank() == 1
                    and not os.path.exists(config["marker"])):
                open(config["marker"], "w").close()
                os._exit(1)  # hard crash, not a python exception
            d = tempfile.mkdtemp()
            train.report({"step": step},
                         Checkpoint.from_pytree({"step": step}, d))

    trainer = train.JaxTrainer(
        loop,
        train_loop_config={"marker": died_marker},
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(
            name="death", storage_path=str(tmp_path),
            failure_config=FailureConfig(
                max_failures=1, restart_backoff_s=0.1,
                health_check_interval_s=0.25)),
    )
    start = time.monotonic()
    result = trainer.fit()
    assert result.error is None, result.error
    # Steps 0,1 from attempt one; resumed at 2 (ckpt step 1), then 2,3.
    assert [m["step"] for m in result.metrics_history] == [0, 1, 2, 3]
    assert time.monotonic() - start < 60.0


def test_torn_checkpoint_never_resumed_e2e(ray_start, tmp_path):
    """fit() on an experiment dir holding a committed checkpoint and a
    later torn one resumes from the committed checkpoint."""
    exp = str(tmp_path / "tornexp")
    os.makedirs(exp)
    _committed_dir(exp, 0, step=1)
    torn = _committed_dir(exp, 1, step=2)
    shard = os.path.join(torn.path, "shard_0.msgpack")
    with open(shard, "r+b") as f:  # prior driver crashed mid-write
        f.truncate(3)

    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            d = tempfile.mkdtemp()
            train.report({"step": step},
                         Checkpoint.from_pytree({"step": step}, d))

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="tornexp",
                                   storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    # Resumed from committed step 1 (not torn step 2): first report is 2.
    assert [m["step"] for m in result.metrics_history] == [2, 3]
    assert result.checkpoint.to_pytree()["step"] == 3


def test_elastic_shrink_to_min_workers(ray_start, tmp_path):
    """When the full gang never becomes placeable, fit re-forms a
    smaller gang (down to min_workers) and re-shards datasets."""

    def loop(config):
        ctx = train.get_context()
        shard = train.get_dataset_shard("train")
        train.report({"world": ctx.get_world_size(),
                      "shard_len": len(list(shard))})

    trainer = train.JaxTrainer(
        loop,
        # 6 x 1 CPU can never place on the 4-CPU test cluster; 4 can.
        scaling_config=train.ScalingConfig(num_workers=6,
                                           cpus_per_worker=1.0),
        run_config=train.RunConfig(
            name="elastic", storage_path=str(tmp_path),
            failure_config=FailureConfig(
                min_workers=2, resource_wait_timeout_s=1.0)),
        datasets={"train": list(range(12))},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["world"] == 4
    assert result.metrics["shard_len"] == 3  # 12 items over 4 ranks


@pytest.mark.chaos
def test_restart_under_fault_injection(ray_start, tmp_path):
    """PR 1's FaultInjector drops task pushes while the trainer rides
    out a worker failure: the unified retry plane + gang restart still
    finish the run from the latest checkpoint."""
    from ray_tpu.core import rpc

    fi = rpc.get_fault_injector()
    fi.install("drop", peer="peer-*", method="push_tasks",
               direction="send", probability=0.2, max_matches=6)

    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 4):
            if step == 2 and start == 0:
                raise RuntimeError("injected failure at step 2")
            d = tempfile.mkdtemp()
            train.report({"step": step},
                         Checkpoint.from_pytree({"step": step}, d))

    try:
        trainer = train.JaxTrainer(
            loop,
            scaling_config=train.ScalingConfig(num_workers=1),
            run_config=train.RunConfig(
                name="faulty", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2,
                                             restart_backoff_s=0.1)),
        )
        result = trainer.fit()
    finally:
        fi.reset()
    assert result.error is None, result.error
    assert [m["step"] for m in result.metrics_history] == [0, 1, 2, 3]


def test_train_worker_killer_validates_mode():
    from ray_tpu.util.chaos import TrainWorkerKiller

    with pytest.raises(ValueError):
        TrainWorkerKiller(mode="maim")
    k = TrainWorkerKiller(mode="hang", hang_s=5.0, max_duration_s=0.1)
    assert k.mode == "hang"


@pytest.mark.slow
@pytest.mark.chaos
def test_soak_chaos_kill_train_worker_reaches_target_loss(
        ray_start, tmp_path):
    """Chaos soak: a TrainWorkerKiller destroys gang actors mid-run;
    the trainer keeps recovering from the latest committed checkpoint
    until the loss target is reached."""
    from ray_tpu.util.chaos import TrainWorkerKiller

    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 25):
            loss = 5.0 * (0.8 ** step)
            time.sleep(0.15)  # give the killer a window mid-step
            d = tempfile.mkdtemp()
            train.report({"step": step, "loss": loss},
                         Checkpoint.from_pytree({"step": step}, d))

    killer = ray_tpu.remote(TrainWorkerKiller).options(
        num_cpus=0.1).remote(
        kill_interval_s=2.0, max_kills=2, seed=7, mode="kill",
        max_duration_s=45.0)
    run_ref = killer.run.remote()
    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(
            name="soak", storage_path=str(tmp_path),
            failure_config=FailureConfig(
                max_failures=6, restart_backoff_s=0.1,
                health_check_interval_s=0.5)),
    )
    try:
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["loss"] < 0.5  # reached target loss
        assert result.metrics["step"] == 24
        killed = ray_tpu.get(killer.get_killed.remote(), timeout=60)
        assert len(killed) >= 1, "chaos run killed nothing — proves nothing"
    finally:
        ray_tpu.get(killer.stop.remote(), timeout=30)
        ray_tpu.kill(killer)


@pytest.mark.slow
@pytest.mark.chaos
def test_soak_chaos_hang_train_worker_recovers(ray_start, tmp_path):
    """Chaos soak, hang flavor: the killer stalls a random rank's train
    loop (RPC lane stays green); the health monitor attributes the hang
    and the restart finishes the run."""
    from ray_tpu.util.chaos import TrainWorkerKiller

    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_pytree()["step"] + 1 if ckpt else 0
        for step in range(start, 12):
            time.sleep(0.1)
            d = tempfile.mkdtemp()
            train.report({"step": step},
                         Checkpoint.from_pytree({"step": step}, d))

    killer = ray_tpu.remote(TrainWorkerKiller).options(
        num_cpus=0.1).remote(
        kill_interval_s=1.0, max_kills=1, seed=3, mode="hang",
        hang_s=30.0, max_duration_s=30.0)
    run_ref = killer.run.remote()
    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        run_config=train.RunConfig(
            name="hangsoak", storage_path=str(tmp_path),
            failure_config=FailureConfig(
                max_failures=4, restart_backoff_s=0.1,
                health_check_interval_s=0.4, hang_timeout_s=2.0)),
    )
    try:
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["step"] == 11
    finally:
        ray_tpu.get(killer.stop.remote(), timeout=30)
        ray_tpu.kill(killer)


def test_trainer_jax_mlp_e2e(ray_start, tmp_path):
    """SURVEY.md §7.2 minimum slice: sharded MLP train loop in a worker
    actor, loss decreasing, sharded-pytree checkpoint reported."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.mlp import MLP
        from ray_tpu.parallel import MeshConfig, create_mesh
        from ray_tpu.train.spmd import make_sharded_train

        mesh = create_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
        model = MLP(features=(16, 4))
        x = jnp.asarray(np.random.RandomState(0).rand(8, 8), jnp.float32)
        y = jnp.asarray(np.arange(8) % 4)
        batch = {"inputs": x, "targets": y}

        def loss_fn(logits, b):
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, b["targets"]).mean()

        init, step_fn, _ = make_sharded_train(
            model, optax.adam(1e-2), mesh, batch, loss_fn,
        )
        state = init(jax.random.PRNGKey(0))
        losses = []
        for i in range(8):
            state, metrics = step_fn(state, batch)
            losses.append(float(metrics["loss"]))
        d = tempfile.mkdtemp()
        ckpt = Checkpoint.from_pytree(
            jax.device_get(state.params), d)
        train.report({"first_loss": losses[0], "last_loss": losses[-1]},
                     ckpt)

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="mlp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["last_loss"] < result.metrics["first_loss"]
    params = result.checkpoint.to_pytree()
    import jax

    assert len(jax.tree.leaves(params)) > 0  # restored non-empty pytree
