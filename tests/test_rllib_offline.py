"""Tests for ray_tpu.rllib offline RL + connectors (reference strategy:
rllib/offline/tests/, rllib/connectors/tests/)."""

import numpy as np
import pytest

from ray_tpu.rllib import (
    BCConfig,
    CastObs,
    ConnectorPipelineV2,
    DirectMethod,
    DoublyRobust,
    FlattenObs,
    FrameStackObs,
    ImportanceSampling,
    JsonReader,
    JsonWriter,
    NormalizeObs,
    WeightedImportanceSampling,
    collect_episodes,
)
from ray_tpu.rllib.env import Space
from ray_tpu.rllib.rl_module import RLModuleSpec


# -- connectors -------------------------------------------------------------


def test_pipeline_surgery():
    pipe = ConnectorPipelineV2([FlattenObs(), CastObs(np.float32)])
    pipe.prepend(FrameStackObs(2))
    pipe.insert_after("FlattenObs", NormalizeObs())
    names = [c.name for c in pipe.connectors]
    assert names == ["FrameStackObs", "FlattenObs", "NormalizeObs",
                     "CastObs"]
    pipe.remove(FrameStackObs)
    assert [c.name for c in pipe.connectors] == [
        "FlattenObs", "NormalizeObs", "CastObs"]


def test_flatten_and_space_transform():
    pipe = ConnectorPipelineV2([FrameStackObs(3), FlattenObs()])
    space = Space.box((4, 4, 2))
    out_space = pipe.transform_space(space)
    assert out_space.shape == (4 * 4 * 6,)
    obs = np.ones((5, 4, 4, 2), np.float32)
    out = pipe({"obs": obs, "dones": None})
    assert out["obs"].shape == (5, 96)


def test_frame_stack_resets_on_done():
    fs = FrameStackObs(3)
    obs1 = np.full((2, 1), 1.0, np.float32)
    out = fs({"obs": obs1, "dones": None})["obs"]
    assert out.shape == (2, 3)
    np.testing.assert_array_equal(out[0], [1, 1, 1])
    obs2 = np.full((2, 1), 2.0, np.float32)
    out = fs({"obs": obs2, "dones": np.array([False, False])})["obs"]
    np.testing.assert_array_equal(out[0], [1, 1, 2])
    # Env 1 finished: its new obs must seed a fresh stack.
    obs3 = np.stack([np.array([3.0], np.float32),
                     np.array([9.0], np.float32)])
    out = fs({"obs": obs3, "dones": np.array([False, True])})["obs"]
    np.testing.assert_array_equal(out[0], [1, 2, 3])
    np.testing.assert_array_equal(out[1], [9, 9, 9])


def test_frame_stack_preview_does_not_mutate():
    fs = FrameStackObs(2)
    fs({"obs": np.full((1, 1), 1.0, np.float32), "dones": None})
    before = fs._stack.copy()
    pv = fs.preview({"obs": np.full((1, 1), 5.0, np.float32),
                     "dones": None})["obs"]
    np.testing.assert_array_equal(pv[0], [1, 5])
    np.testing.assert_array_equal(fs._stack, before)


def test_normalize_obs_converges():
    norm = NormalizeObs()
    rng = np.random.default_rng(0)
    for _ in range(50):
        norm({"obs": rng.normal(5.0, 2.0, (64, 3)).astype(np.float32),
              "dones": None})
    out = norm({"obs": rng.normal(5.0, 2.0, (512, 3)).astype(np.float32),
                "dones": None})["obs"]
    assert abs(float(out.mean())) < 0.15
    assert abs(float(out.std()) - 1.0) < 0.15
    # preview must not advance the statistics
    count = norm._count
    norm.preview({"obs": np.zeros((8, 3), np.float32), "dones": None})
    assert norm._count == count


def test_connectors_in_env_runner():
    from ray_tpu.rllib.env_runner import EnvRunner

    spec = RLModuleSpec(Space.box((4 * 2,)), Space.discrete(2))
    runner = EnvRunner("CartPole-v1", 4, 16, spec, seed=0,
                       env_to_module=[lambda: FrameStackObs(2)])
    batch = runner.sample()
    assert batch["obs"].shape == (16, 4, 8)  # 4-dim obs stacked x2


@pytest.fixture(scope="module")
def rl_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_connectors_through_algorithm(rl_cluster):
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1", num_envs_per_env_runner=4)
        .env_runners(num_env_runners=1, rollout_fragment_length=16)
        .training(train_batch_size=64, minibatch_size=32, num_epochs=1)
        .connectors(env_to_module=[lambda: FrameStackObs(2)])
        .build()
    )
    try:
        result = algo.step()
        assert result["timesteps_total"] > 0
    finally:
        algo.stop()


# -- offline IO -------------------------------------------------------------


def _make_episodes(n=20, T=10, seed=0):
    rng = np.random.default_rng(seed)
    eps = []
    for _ in range(n):
        eps.append({
            "obs": rng.normal(size=(T + 1, 4)).astype(np.float32),
            "actions": rng.integers(0, 2, T).astype(np.int32),
            "rewards": np.ones(T, np.float32),
            "logp": np.full(T, np.log(0.5), np.float32),
            "terminated": True,
        })
    return eps


def test_json_writer_reader_roundtrip(tmp_path):
    eps = _make_episodes(7, T=5)
    with JsonWriter(str(tmp_path / "out"),
                    max_episodes_per_file=3) as w:
        for ep in eps:
            w.write(ep)
    reader = JsonReader(str(tmp_path / "out"))
    assert reader.obs_shape == (4,)
    assert reader.num_actions == 2
    back = list(reader.read_episodes())
    assert len(back) == 7
    np.testing.assert_allclose(back[0]["obs"], eps[0]["obs"])
    np.testing.assert_array_equal(back[3]["actions"], eps[3]["actions"])
    trans = reader.to_transitions()
    assert trans["obs"].shape == (35, 4)
    assert trans["dones"].sum() == 7  # one terminal per episode


def test_collect_episodes_cartpole(tmp_path):
    import jax

    spec = RLModuleSpec(Space.box((4,)), Space.discrete(2))
    params = spec.build().init_params(jax.random.PRNGKey(0))
    writer = JsonWriter(str(tmp_path / "cp"))
    eps = collect_episodes("CartPole-v1", spec, params,
                           num_episodes=5, num_envs=4, seed=0,
                           writer=writer)
    writer.close()
    assert len(eps) == 5
    for ep in eps:
        T = len(ep["actions"])
        assert ep["obs"].shape == (T + 1, 4)
        assert ep["rewards"].shape == (T,)
        assert np.all(ep["logp"] <= 0)
    reader = JsonReader(str(tmp_path / "cp"))
    assert len(list(reader.read_episodes())) >= 5


# -- off-policy estimators --------------------------------------------------


def test_is_wis_identity_policy():
    """Target == behavior -> v_target ~= v_behavior (weights ~1)."""
    import jax

    spec = RLModuleSpec(Space.box((4,)), Space.discrete(2))
    params = spec.build().init_params(jax.random.PRNGKey(0))
    eps = collect_episodes("CartPole-v1", spec, params,
                           num_episodes=10, num_envs=4, seed=1)
    for cls in (ImportanceSampling, WeightedImportanceSampling):
        est = cls(spec, params, gamma=1.0)
        out = est.estimate(eps)
        assert out["num_episodes"] == 10
        # Same policy: the IS estimate equals the behavior return
        # exactly (weights == 1) up to float noise.
        assert out["v_gain"] == pytest.approx(1.0, rel=0.05), cls
        assert out["v_target"] == pytest.approx(out["v_behavior"],
                                                rel=0.05)


def test_is_detects_better_policy():
    """A target policy preferring the rewarded action must score higher
    than a uniform behavior policy on a synthetic bandit."""
    import jax
    import jax.numpy as jnp

    spec = RLModuleSpec(Space.box((2,)), Space.discrete(2))
    module = spec.build()
    params = module.init_params(jax.random.PRNGKey(0))
    # Steer logits toward action 1 by biasing the output layer.
    flat = params["params"]
    last = [k for k in flat if k.startswith("Dense")][-2]  # logits head

    def bias_toward_one(p):
        b = np.zeros_like(np.asarray(p["bias"]))
        b[1] = 4.0  # ~98% action 1
        return {"kernel": jnp.zeros_like(p["kernel"]),
                "bias": jnp.asarray(b)}

    flat[last] = bias_toward_one(flat[last])
    # Behavior: uniform random; reward 1 only for action 1.
    rng = np.random.default_rng(0)
    eps = []
    for _ in range(40):
        T = 6
        acts = rng.integers(0, 2, T).astype(np.int32)
        eps.append({
            "obs": np.zeros((T + 1, 2), np.float32),
            "actions": acts,
            "rewards": acts.astype(np.float32),
            "logp": np.full(T, np.log(0.5), np.float32),
            "terminated": True,
        })
    est = WeightedImportanceSampling(spec, params, gamma=1.0)
    out = est.estimate(eps)
    # Behavior earns ~3 of 6; target should be near 6.
    assert out["v_behavior"] == pytest.approx(3.0, abs=0.8)
    assert out["v_target"] > out["v_behavior"] * 1.4


def test_dm_and_dr_estimate():
    """DM/DR on the synthetic bandit: the FQE model learns Q(s, a) = a
    (immediate reward), so both should score the action-1 policy near
    its true value."""
    import jax
    import jax.numpy as jnp

    spec = RLModuleSpec(Space.box((2,)), Space.discrete(2))
    module = spec.build()
    params = module.init_params(jax.random.PRNGKey(0))
    flat = params["params"]
    last = [k for k in flat if k.startswith("Dense")][-2]
    b = np.zeros_like(np.asarray(flat[last]["bias"]))
    b[1] = 4.0
    flat[last] = {"kernel": jnp.zeros_like(flat[last]["kernel"]),
                  "bias": jnp.asarray(b)}
    rng = np.random.default_rng(1)
    eps = []
    for _ in range(30):
        T = 4
        acts = rng.integers(0, 2, T).astype(np.int32)
        eps.append({
            "obs": np.zeros((T + 1, 2), np.float32),
            "actions": acts,
            "rewards": acts.astype(np.float32),
            "logp": np.full(T, np.log(0.5), np.float32),
            "terminated": True,
        })
    for cls in (DirectMethod, DoublyRobust):
        est = cls(spec, params, gamma=1.0, fqe_iterations=1000)
        out = est.estimate(eps)
        # True target value ~= 3.92 (0.98 * 4 steps); behavior ~2. DR
        # carries IS variance on 30 episodes, so the band is wide.
        assert out["v_target"] > out["v_behavior"] * 1.3, cls
        assert 2.5 < out["v_target"] < 4.8, (cls, out)


# -- behavior cloning -------------------------------------------------------


def test_bc_learns_dataset_policy(tmp_path):
    """BC on an expert dataset (always action 1) should drive the
    policy toward action 1."""
    import jax

    rng = np.random.default_rng(0)
    with JsonWriter(str(tmp_path / "expert")) as w:
        for _ in range(20):
            T = 8
            w.write({
                "obs": rng.normal(size=(T + 1, 3)).astype(np.float32),
                "actions": np.ones(T, np.int32),
                "rewards": np.ones(T, np.float32),
                "logp": np.zeros(T, np.float32),
                "terminated": True,
            })
    algo = (
        BCConfig()
        .offline_data(input_=str(tmp_path / "expert"))
        .training(lr=1e-2, train_batch_size=64)
        .debugging(seed=0)
        .build()
    )
    first = algo.step()
    for _ in range(30):
        last = algo.step()
    assert last["bc_loss"] < first["bc_loss"]
    # The trained policy should now prefer action 1 everywhere.
    spec = algo.module_spec
    module = spec.build()
    forwards = module.make_forwards()
    obs = rng.normal(size=(32, 3)).astype(np.float32)
    acts = np.asarray(forwards["inference"](
        algo.get_policy_params(), obs))
    assert (acts == 1).mean() > 0.9
    # state roundtrip
    state = algo.get_state()
    algo2 = (BCConfig().offline_data(input_=str(tmp_path / "expert"))
             .build())
    algo2.set_state(state)
    acts2 = np.asarray(forwards["inference"](
        algo2.get_policy_params(), obs))
    np.testing.assert_array_equal(acts, acts2)


def test_writer_header_num_actions_not_frozen(tmp_path):
    """First episode lacks the highest action id: the reader must still
    report the full cardinality (via meta.json, not shard-0's header)."""
    rng = np.random.default_rng(0)

    def ep(actions):
        a = np.asarray(actions, np.int32)
        T = len(a)
        return {"obs": rng.normal(size=(T + 1, 2)).astype(np.float32),
                "actions": a, "rewards": np.ones(T, np.float32),
                "logp": np.zeros(T, np.float32), "terminated": True}

    with JsonWriter(str(tmp_path / "d"), max_episodes_per_file=1) as w:
        w.write(ep([0, 0, 0]))   # shard 0 header says num_actions=1
        w.write(ep([0, 2, 1]))
    reader = JsonReader(str(tmp_path / "d"))
    assert reader.num_actions == 3


def test_collect_writer_matches_return(tmp_path):
    import jax

    spec = RLModuleSpec(Space.box((4,)), Space.discrete(2))
    params = spec.build().init_params(jax.random.PRNGKey(0))
    w = JsonWriter(str(tmp_path / "m"))
    eps = collect_episodes("CartPole-v1", spec, params,
                           num_episodes=3, num_envs=8, seed=2, writer=w)
    w.close()
    on_disk = list(JsonReader(str(tmp_path / "m")).read_episodes())
    assert len(eps) == 3
    assert len(on_disk) == 3


def test_eval_copy_isolates_and_freezes():
    from ray_tpu.rllib.connectors import ConnectorPipelineV2

    norm = NormalizeObs()
    fs = FrameStackObs(2)
    pipe = ConnectorPipelineV2([norm, fs])
    rng = np.random.default_rng(0)
    for _ in range(10):
        pipe({"obs": rng.normal(3.0, 1.0, (8, 2)).astype(np.float32),
              "dones": None})
    count_before = norm._count
    ev = pipe.eval_copy()
    # Learned stats inherited but frozen; frame stack dropped.
    ev_norm, ev_fs = ev.connectors
    assert ev_norm._count == count_before and not ev_norm.update
    assert ev_fs._stack is None
    ev({"obs": np.zeros((8, 2), np.float32), "dones": None})
    # Training pipeline untouched by the eval copy's use.
    assert norm._count == count_before
    assert norm.update
    assert fs._stack is not None
