"""Distributed tracing (reference strategy: test_tracing.py — spans for
submit + execute, worker span parented to the driver's). This image
ships opentelemetry-api only, so the built-in mini backend is what runs;
the assertions go through the backend-neutral public API."""

import ray_tpu
from ray_tpu.util import tracing


def test_span_parenting_roundtrip():
    assert tracing.setup_tracing("test-svc")
    with tracing.submit_span("mytask") as parent:
        carrier = tracing.inject_context()
    assert carrier and "traceparent" in carrier
    with tracing.task_span("mytask", carrier):
        pass
    if tracing.backend() == "mini":
        spans = {s["name"]: s for s in tracing.get_recorded_spans()}
        sub, ex = spans["submit mytask"], spans["execute mytask"]
        assert ex["trace_id"] == sub["trace_id"]
        assert ex["parent_id"] == sub["span_id"]


def test_trace_ctx_rides_task_kwargs(ray_start):
    """The hidden _rtpu_trace_ctx kwarg is stripped before user code
    runs; the worker records an execute-span in the same trace."""
    tracing.setup_tracing("test-e2e")

    @ray_tpu.remote
    def echo_kwargs(**kw):
        from ray_tpu.util import tracing as wtracing

        # Inside the task, the ACTIVE span is the worker's execute
        # span; its carrier exposes the trace id it was parented to.
        return sorted(kw), wtracing.inject_context()

    with tracing.submit_span("outer") as outer:
        outer_carrier = tracing.inject_context()
        keys, task_carrier = ray_tpu.get(
            echo_kwargs.remote(a=1, b=2), timeout=120)
    assert keys == ["a", "b"]
    assert task_carrier and "traceparent" in task_carrier
    # Same trace across the process boundary.
    assert (task_carrier["traceparent"].split("-")[1]
            == outer_carrier["traceparent"].split("-")[1])


def test_generic_span_parents_to_carrier():
    tracing.setup_tracing("test-span")
    with tracing.span("parent"):
        carrier = tracing.inject_context()
    with tracing.span("child", carrier):
        pass
    if tracing.backend() == "mini":
        spans = {s["name"]: s for s in tracing.get_recorded_spans()}
        assert spans["child"]["trace_id"] == spans["parent"]["trace_id"]
        assert spans["child"]["parent_id"] == spans["parent"]["span_id"]


def test_rpc_spans_gated_on_config_flag(monkeypatch):
    """trace_rpc=1 wraps Connection.call / handler dispatch in
    client+server spans sharing one trace; off by default."""
    from ray_tpu.core import rpc

    tracing.setup_tracing("test-rpc-span")
    assert rpc._rpc_tracing_on() is False  # default off (warms cache)
    monkeypatch.setattr(rpc, "_trace_rpc_flag", True)

    lt = rpc.EventLoopThread(name="trace-rpc-test-io")

    async def h_echo(conn, payload):
        return {"v": payload["v"]}

    server = rpc.Server({"echo": h_echo}, name="tsrv")
    try:
        port = lt.run(server.start("127.0.0.1", 0))
        conn = lt.run(rpc.connect("127.0.0.1", port, {}, name="tcli"))
        assert lt.run(conn.call("echo", {"v": 7}, timeout=10)) == {"v": 7}
        lt.run(conn.close(), timeout=5)
        lt.run(server.stop(), timeout=5)
    finally:
        lt.stop()

    if tracing.backend() == "mini":
        spans = tracing.get_recorded_spans()
        client = [s for s in spans if s["name"] == "rpc echo"]
        handler = [s for s in spans if s["name"] == "rpc.handle echo"]
        assert client and handler
        assert handler[-1]["trace_id"] == client[-1]["trace_id"]
