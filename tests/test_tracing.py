"""Distributed tracing (reference strategy: test_tracing.py — spans for
submit + execute, worker span parented to the driver's). This image
ships opentelemetry-api only, so the built-in mini backend is what runs;
the assertions go through the backend-neutral public API."""

import ray_tpu
from ray_tpu.util import tracing


def test_span_parenting_roundtrip():
    assert tracing.setup_tracing("test-svc")
    with tracing.submit_span("mytask") as parent:
        carrier = tracing.inject_context()
    assert carrier and "traceparent" in carrier
    with tracing.task_span("mytask", carrier):
        pass
    if tracing.backend() == "mini":
        spans = {s["name"]: s for s in tracing.get_recorded_spans()}
        sub, ex = spans["submit mytask"], spans["execute mytask"]
        assert ex["trace_id"] == sub["trace_id"]
        assert ex["parent_id"] == sub["span_id"]


def test_trace_ctx_rides_task_kwargs(ray_start):
    """The hidden _rtpu_trace_ctx kwarg is stripped before user code
    runs; the worker records an execute-span in the same trace."""
    tracing.setup_tracing("test-e2e")

    @ray_tpu.remote
    def echo_kwargs(**kw):
        from ray_tpu.util import tracing as wtracing

        # Inside the task, the ACTIVE span is the worker's execute
        # span; its carrier exposes the trace id it was parented to.
        return sorted(kw), wtracing.inject_context()

    with tracing.submit_span("outer") as outer:
        outer_carrier = tracing.inject_context()
        keys, task_carrier = ray_tpu.get(
            echo_kwargs.remote(a=1, b=2), timeout=120)
    assert keys == ["a", "b"]
    assert task_carrier and "traceparent" in task_carrier
    # Same trace across the process boundary.
    assert (task_carrier["traceparent"].split("-")[1]
            == outer_carrier["traceparent"].split("-")[1])
