"""Core task API tests (reference test model: python/ray/tests/test_basic.py)."""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def fail(msg):
    raise RuntimeError(msg)


def test_submit_and_get(ray_start):
    assert ray_tpu.get(add.remote(1, 2), timeout=60) == 3


def test_many_tasks(ray_start):
    refs = [add.remote(i, i) for i in range(50)]
    assert ray_tpu.get(refs, timeout=60) == [2 * i for i in range(50)]


def test_kwargs(ray_start):
    assert ray_tpu.get(add.remote(a=2, b=3), timeout=60) == 5


def test_task_error(ray_start):
    with pytest.raises(exc.TaskError) as info:
        ray_tpu.get(fail.remote("boom"), timeout=60)
    assert "boom" in str(info.value)
    assert info.value.cause_cls_name == "RuntimeError"


def test_nested_task_error_propagates(ray_start):
    @ray_tpu.remote
    def outer():
        return ray_tpu.get(fail.remote("inner"), timeout=30)

    with pytest.raises(exc.TaskError) as info:
        ray_tpu.get(outer.remote(), timeout=60)
    assert "inner" in str(info.value)


def test_num_returns(ray_start):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3], timeout=60) == [1, 2, 3]


def test_options_override(ray_start):
    f = add.options(name="custom-add", num_cpus=0.5)
    assert ray_tpu.get(f.remote(4, 5), timeout=60) == 9


def test_pass_ref_as_arg(ray_start):
    ref = add.remote(1, 1)
    ref2 = add.remote(ref, 1)
    assert ray_tpu.get(ref2, timeout=60) == 3


def test_direct_call_raises(ray_start):
    with pytest.raises(TypeError):
        add(1, 2)


def test_nested_submission(ray_start):
    @ray_tpu.remote
    def outer(n):
        refs = [add.remote(i, 1) for i in range(n)]
        return sum(ray_tpu.get(refs, timeout=30))

    assert ray_tpu.get(outer.remote(4), timeout=90) == 10


def test_wait(ray_start):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = add.remote(0, 1)
    refs = [fast, slow.remote(30)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=1, timeout=30)
    assert ready == [fast]
    assert len(not_ready) == 1


def test_wait_timeout(ray_start):
    @ray_tpu.remote
    def sleepy():
        time.sleep(60)

    ready, not_ready = ray_tpu.wait([sleepy.remote()], num_returns=1,
                                    timeout=0.5)
    assert ready == []
    assert len(not_ready) == 1


def test_retry_on_worker_death(ray_start):
    @ray_tpu.remote(max_retries=2)
    def die_once(marker):
        import os

        path = f"/tmp/ray_tpu_die_once_{marker}"
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        os.remove(path)
        return "survived"

    marker = str(time.time()).replace(".", "")
    assert ray_tpu.get(die_once.remote(marker), timeout=240) == "survived"


def test_retry_on_worker_death_stress(ray_start):
    """Several concurrent worker-suicide tasks: exercises the
    return-lease-before-death-detected race (a dead worker must never be
    re-idled and re-granted, and a failed lease request must re-pump)."""
    @ray_tpu.remote(max_retries=2)
    def die_once(marker):
        import os

        path = f"/tmp/ray_tpu_die_once_{marker}"
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)
        os.remove(path)
        return marker

    base = str(time.time()).replace(".", "")
    markers = [f"{base}_{i}" for i in range(5)]
    refs = [die_once.remote(m) for m in markers]
    assert ray_tpu.get(refs, timeout=240) == markers


def test_no_retry_exhausted(ray_start):
    @ray_tpu.remote(max_retries=0)
    def always_die():
        import os

        os._exit(1)

    with pytest.raises(exc.WorkerCrashedError):
        ray_tpu.get(always_die.remote(), timeout=240)


def test_get_timeout(ray_start):
    @ray_tpu.remote
    def forever():
        time.sleep(120)

    with pytest.raises(exc.GetTimeoutError):
        ray_tpu.get(forever.remote(), timeout=1.0)


def test_runtime_context(ray_start):
    ctx = ray_tpu.get_runtime_context()
    assert len(ctx.job_id) == 8
    assert ctx.worker_id


def test_cluster_resources(ray_start):
    res = ray_tpu.cluster_resources()
    assert res.get("CPU") == 4.0
