"""Collective layer tests (reference strategy:
python/ray/util/collective/tests/ — rank actors exercising each op)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import collective as col


@ray_tpu.remote
class Rank:
    def setup(self, world_size, rank, group_name):
        col.init_collective_group(world_size, rank, backend="host",
                                  group_name=group_name)
        self.rank = rank
        self.world = world_size
        self.group = group_name
        return rank

    def do_allreduce(self, value):
        return col.allreduce(np.full((4,), value, np.float32),
                             group_name=self.group)

    def do_allgather(self):
        return col.allgather(np.array([self.rank], np.int64),
                             group_name=self.group)

    def do_broadcast(self):
        t = np.arange(3, dtype=np.float32) if self.rank == 1 else \
            np.zeros(3, np.float32)
        return col.broadcast(t, src_rank=1, group_name=self.group)

    def do_reducescatter(self):
        # Each rank contributes [0..world*2); sum chunked over ranks.
        t = np.arange(self.world * 2, dtype=np.float32)
        return col.reducescatter(t, group_name=self.group)

    def do_barrier(self):
        col.barrier(group_name=self.group)
        return self.rank

    def do_alltoall(self):
        tensors = [np.array([self.rank * 10 + j]) for j in range(self.world)]
        return col.alltoall(tensors, group_name=self.group)

    def do_sendrecv(self):
        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=self.group)
            return None
        return col.recv(np.zeros(1), src_rank=0, group_name=self.group)

    def query(self):
        return (col.get_rank(self.group),
                col.get_collective_group_size(self.group),
                col.is_group_initialized(self.group))


@pytest.fixture(scope="module")
def group(ray_start):
    world = 3
    actors = [Rank.remote() for _ in range(world)]
    ray_tpu.get([a.setup.remote(world, i, "g1")
                 for i, a in enumerate(actors)])
    yield actors
    for a in actors:
        ray_tpu.kill(a)


def test_allreduce(group):
    results = ray_tpu.get([a.do_allreduce.remote(float(i + 1))
                           for i, a in enumerate(group)])
    for r in results:
        np.testing.assert_allclose(r, np.full((4,), 6.0))


def test_allgather(group):
    results = ray_tpu.get([a.do_allgather.remote() for a in group])
    for r in results:
        assert [int(x[0]) for x in r] == [0, 1, 2]


def test_broadcast(group):
    results = ray_tpu.get([a.do_broadcast.remote() for a in group])
    for r in results:
        np.testing.assert_allclose(r, np.arange(3, dtype=np.float32))


def test_reducescatter(group):
    results = ray_tpu.get([a.do_reducescatter.remote() for a in group])
    world = len(group)
    full = np.arange(world * 2, dtype=np.float32) * world
    for rank, r in enumerate(results):
        np.testing.assert_allclose(r, full[rank * 2:(rank + 1) * 2])


def test_barrier_and_introspection(group):
    assert sorted(ray_tpu.get([a.do_barrier.remote() for a in group])) == \
        [0, 1, 2]
    infos = ray_tpu.get([a.query.remote() for a in group])
    assert infos == [(0, 3, True), (1, 3, True), (2, 3, True)]


def test_alltoall(group):
    results = ray_tpu.get([a.do_alltoall.remote() for a in group])
    # rank j receives [i*10+j for each source rank i]
    for j, r in enumerate(results):
        assert [int(x[0]) for x in r] == [i * 10 + j for i in range(3)]


def test_send_recv(group):
    out = ray_tpu.get([group[0].do_sendrecv.remote(),
                       group[1].do_sendrecv.remote()])
    assert out[0] is None
    np.testing.assert_allclose(out[1], np.array([42.0]))


@ray_tpu.remote
class LazyRank:
    def op(self, group_name):
        # No init_collective_group call: rank resolved from the store's
        # membership table on first op.
        return col.allreduce(np.ones(2, np.float32), group_name=group_name)

    def rank(self, group_name):
        return col.get_rank(group_name)


def test_list_declared_groups_and_destroy_sweep(ray_start):
    """Cluster-wide group introspection: declared groups are visible
    from the driver and disappear after destroy — the gang-abort flow's
    forensics surface."""
    col.init_collective_group(1, 0, group_name="g_listed")
    assert "g_listed" in col.list_declared_groups()
    assert "g_listed" in col.local_group_names()
    col.destroy_collective_group("g_listed")
    assert "g_listed" not in col.list_declared_groups()
    assert "g_listed" not in col.local_group_names()


def test_declarative_group(ray_start):
    world = 2
    actors = [LazyRank.remote() for _ in range(world)]
    col.create_collective_group(actors, world, list(range(world)),
                                backend="host", group_name="g_lazy")
    results = ray_tpu.get([a.op.remote("g_lazy") for a in actors])
    for r in results:
        np.testing.assert_allclose(r, np.array([2.0, 2.0]))
    assert sorted(ray_tpu.get([a.rank.remote("g_lazy")
                               for a in actors])) == [0, 1]
    for a in actors:
        ray_tpu.kill(a)


def test_destroy_wakes_blocked_waiters(ray_start):
    import time

    @ray_tpu.remote
    class Straggler:
        def setup(self, world, rank):
            col.init_collective_group(world, rank, backend="host",
                                      group_name="g_destroy")
        def blocked_barrier(self):
            try:
                col.barrier(group_name="g_destroy")
                return "completed"
            except Exception:
                return "raised"

    actors = [Straggler.remote() for _ in range(2)]
    ray_tpu.get([a.setup.remote(2, i) for i, a in enumerate(actors)])
    # Only rank 0 enters the barrier; rank 1 never arrives.
    ref = actors[0].blocked_barrier.remote()
    time.sleep(0.5)
    col.destroy_collective_group("g_destroy")
    assert ray_tpu.get(ref, timeout=10) == "raised"
    for a in actors:
        ray_tpu.kill(a)


def test_group_recreate_after_destroy(ray_start):
    """Generation bump: a destroyed group can be recreated and stale
    contexts fail fast instead of desynchronizing the new incarnation."""

    @ray_tpu.remote
    class R:
        def init(self, world, rank, name):
            col.init_collective_group(world, rank, group_name=name)
        def reduce(self, name):
            return col.allreduce(np.ones(1, np.float32), group_name=name)

    a1 = [R.remote() for _ in range(2)]
    ray_tpu.get([a.init.remote(2, i, "g_regen") for i, a in enumerate(a1)])
    ray_tpu.get([a.reduce.remote("g_regen") for a in a1])
    col.destroy_collective_group("g_regen")
    # Old members' stale contexts now error (not hang).
    with pytest.raises(Exception):
        ray_tpu.get(a1[0].reduce.remote("g_regen"), timeout=30)
    # Fresh gang on the same name works.
    a2 = [R.remote() for _ in range(2)]
    ray_tpu.get([a.init.remote(2, i, "g_regen") for i, a in enumerate(a2)])
    out = ray_tpu.get([a.reduce.remote("g_regen") for a in a2])
    for r in out:
        np.testing.assert_allclose(r, np.array([2.0]))
    for a in a1 + a2:
        ray_tpu.kill(a)


def test_create_group_validates_ranks(ray_start):
    a = [object(), object()]
    with pytest.raises(ValueError):
        col.create_collective_group(a, 2, [0, 0], group_name="g_bad")
    with pytest.raises(ValueError):
        col.create_collective_group(a, 2, [1, 2], group_name="g_bad2")
