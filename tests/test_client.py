"""Thin-client mode (reference strategy: util/client tests — a driver
behind a single outbound connection runs tasks/actors/data ops)."""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def client_cluster():
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    head_port, client_port = free_port(), free_port()
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root,
           "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.head_main",
         "--port", str(head_port), "--num-cpus", "4",
         "--client-server-port", str(client_port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    deadline = time.monotonic() + 90
    seen = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        seen += line
        if "client server on" in line:
            break
        if proc.poll() is not None:
            raise RuntimeError(f"head died: {seen}")
    else:
        proc.kill()
        raise TimeoutError(f"client server never started: {seen}")
    yield client_port
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()


@pytest.fixture()
def client(client_cluster):
    ray_tpu.init(address=f"rtpu://127.0.0.1:{client_cluster}")
    yield
    ray_tpu.shutdown()


def test_client_tasks_and_data(client):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=120) == 3
    # refs as args cross the proxy
    r1 = add.remote(10, 20)
    assert ray_tpu.get(add.remote(r1, 5), timeout=120) == 35
    # put/get roundtrip
    ref = ray_tpu.put({"k": [1, 2, 3]})
    assert ray_tpu.get(ref, timeout=60) == {"k": [1, 2, 3]}
    # wait
    refs = [add.remote(i, i) for i in range(4)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=4, timeout=120)
    assert len(ready) == 4 and not_ready == []
    # errors propagate with the original type

    @ray_tpu.remote
    def boom():
        raise ValueError("client boom")

    from ray_tpu import exceptions as exc

    with pytest.raises(exc.TaskError, match="client boom"):
        ray_tpu.get(boom.remote(), timeout=120)


def test_client_actors(client):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def inc(self, by=1):
            self.v += by
            return self.v

    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote(), timeout=120) == 11
    assert ray_tpu.get(c.inc.remote(5), timeout=60) == 16
    ray_tpu.kill(c)


def test_client_head_relay(client):
    # Head RPCs (kv, cluster state) relay through the proxy.
    ray_tpu.kv_put(b"client-key", b"client-val")
    assert ray_tpu.kv_get(b"client-key") == b"client-val"
    res = ray_tpu.cluster_resources()
    assert res.get("CPU") == 4.0


def test_client_named_actor_and_errors(client):
    @ray_tpu.remote
    class Named:
        def who(self):
            return "named-one"

    Named.options(name="client_named", lifetime="detached").remote()
    h = ray_tpu.get_actor("client_named")
    assert ray_tpu.get(h.who.remote(), timeout=120) == "named-one"
    ray_tpu.kill(h)
    # Streaming is a clean error through the client, not a hang.

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1

    with pytest.raises(Exception, match="not supported"):
        gen.remote()
