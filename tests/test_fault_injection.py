"""Network fault-injection plane + unified retry policy.

Covers, with deterministic seeds:
- FaultInjector rule semantics (drop / delay / duplicate / partition,
  peer+method filters, max_matches/duration expiry, seeded determinism)
  at the unit level and over real socket connections,
- RetryPolicy backoff, ConnectionLost.sent at-most-once semantics,
  deadline propagation, polling, and the CircuitBreaker,
- a scripted partition between a driver and an actor's host healed by
  the unified RetryPolicy (retry count observable > 0),
- the GCS node-death grace window: a briefly partitioned node agent is
  NOT declared dead and reattaches with its node id.

Fast variants run in tier-1; long soak variants are marked ``slow``.
The whole lane carries the ``chaos`` marker (``pytest -m chaos``).
"""

import asyncio
import os
import threading
import time

import pytest

from ray_tpu.core import retry, rpc

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# injector: unit level
# ---------------------------------------------------------------------------


def test_injector_disabled_by_default():
    # Must run before any test in this file touches get_fault_injector:
    # the hot send path's disabled-plane cost is one None check, which
    # requires that nothing instantiates the injector as a side effect.
    assert rpc._fault_injector is None


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    if rpc._fault_injector is not None:
        rpc._fault_injector.reset()


def test_rule_matching_filters():
    fi = rpc.FaultInjector(seed=0)
    fi.install("drop", peer="peer-*", method="push_tasks",
               direction="send")
    assert fi.on_frame("send", "peer-4021", "push_tasks") == ("drop", 0.0)
    assert fi.on_frame("send", "agent-head", "push_tasks") is None
    assert fi.on_frame("send", "peer-4021", "kv_get") is None
    assert fi.on_frame("recv", "peer-4021", "push_tasks") is None
    # Response frames (method None) only match wildcard-method rules.
    assert fi.on_frame("send", "peer-4021", None) is None
    fi.install("partition", peer="peer-9*", method="*")
    assert fi.on_frame("send", "peer-9001", None) == ("partition", 0.0)


def test_rule_expiry_by_matches_and_duration():
    fi = rpc.FaultInjector(seed=0)
    fi.install("drop", method="echo", max_matches=2)
    assert fi.on_frame("send", "c", "echo") is not None
    assert fi.on_frame("send", "c", "echo") is not None
    assert fi.on_frame("send", "c", "echo") is None  # budget spent
    rid = fi.install("drop", method="echo", duration_s=0.05)
    assert fi.on_frame("send", "c", "echo") is not None
    time.sleep(0.08)
    assert fi.on_frame("send", "c", "echo") is None  # expired
    # And targeted clear of an already-expired rule is a no-op.
    fi.clear(rid)


def test_seeded_determinism():
    def decisions(seed):
        fi = rpc.FaultInjector(seed=seed)
        fi.install("drop", method="m", probability=0.5)
        return [fi.on_frame("send", "c", "m") is not None
                for _ in range(64)]

    a, b = decisions(7), decisions(7)
    assert a == b
    assert a != decisions(8)
    assert any(a) and not all(a)  # probability actually applied


def test_install_clear_stats():
    fi = rpc.FaultInjector(seed=0)
    r1 = fi.install("delay", method="a", delay_s=0.1)
    r2 = fi.install("drop", method="b")
    assert fi.on_frame("send", "c", "a") == ("delay", pytest.approx(0.1))
    fi.clear(r1)
    assert fi.on_frame("send", "c", "a") is None
    assert fi.on_frame("send", "c", "b") is not None
    fi.clear()
    assert fi.on_frame("send", "c", "b") is None
    assert fi.stats["delay"] == 1 and fi.stats["drop"] == 1
    with pytest.raises(ValueError):
        fi.install("explode")
    assert r2 != r1


# ---------------------------------------------------------------------------
# retry policy: unit level
# ---------------------------------------------------------------------------


def test_backoff_series_deterministic():
    p = retry.RetryPolicy(base_delay_s=0.1, multiplier=2.0,
                          max_delay_s=0.5, jitter=0.0)
    assert list(p.backoff_series(5)) == [0.0, 0.1, 0.2, 0.4, 0.5]


def test_backoff_jitter_bounds():
    p = retry.RetryPolicy(base_delay_s=0.1, multiplier=1.0, jitter=0.5,
                          seed=3)
    for _ in range(100):
        assert 0.05 <= p.backoff_delay(0) <= 0.15


def test_execute_retries_transient_then_succeeds():
    p = retry.RetryPolicy(max_attempts=4, base_delay_s=0.01, jitter=0.0)
    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise rpc.ConnectionLost("blip", sent=False)
        return "ok"

    assert asyncio.run(p.execute(flaky)) == "ok"
    assert calls["n"] == 3
    assert p.total_retries == 2


def test_execute_honors_sent_semantics():
    # sent=True + non-idempotent => at-most-once, no retry.
    p = retry.RetryPolicy(max_attempts=5, base_delay_s=0.01, jitter=0.0)

    async def lost_after_send():
        raise rpc.ConnectionLost("late", sent=True)

    with pytest.raises(rpc.ConnectionLost):
        asyncio.run(p.execute(lost_after_send, idempotent=False))
    assert p.total_retries == 0

    # sent=False is always a free retry, even non-idempotent.
    calls = {"n": 0}

    async def lost_before_send():
        calls["n"] += 1
        if calls["n"] == 1:
            raise rpc.ConnectionLost("early", sent=False)
        return 1

    assert asyncio.run(p.execute(lost_before_send, idempotent=False)) == 1
    assert p.total_retries == 1


def test_execute_never_replays_remote_errors():
    # Plain RpcError = the remote handler raised; deterministic, and
    # replaying it could duplicate side effects.
    p = retry.RetryPolicy(max_attempts=5, base_delay_s=0.01, jitter=0.0)
    calls = {"n": 0}

    async def app_error():
        calls["n"] += 1
        raise rpc.RpcError("ValueError: bad input")

    with pytest.raises(rpc.RpcError):
        asyncio.run(p.execute(app_error))
    assert calls["n"] == 1


def test_execute_deadline_propagation():
    p = retry.RetryPolicy(max_attempts=50, base_delay_s=0.05,
                          multiplier=1.0, jitter=0.0)

    async def always_down():
        raise OSError("unreachable")

    start = time.monotonic()
    with pytest.raises(OSError):
        asyncio.run(p.execute(always_down, deadline_s=0.3))
    # Stopped by the deadline, far before 50 attempts' worth of sleeping.
    assert time.monotonic() - start < 1.5


def test_execute_sync():
    p = retry.RetryPolicy(max_attempts=3, base_delay_s=0.01, jitter=0.0)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise OSError("blip")
        return "ok"

    assert p.execute_sync(flaky) == "ok"
    assert p.total_retries == 1
    with pytest.raises(ValueError):
        p.execute_sync(lambda: (_ for _ in ()).throw(ValueError("app")))


def test_poll_until_predicate():
    p = retry.RetryPolicy(base_delay_s=0.01, jitter=0.0)
    state = {"n": 0}

    async def probe():
        state["n"] += 1
        return state["n"]

    assert asyncio.run(p.poll(probe, predicate=lambda v: v >= 3,
                              deadline_s=5.0)) == 3
    with pytest.raises(retry.PollTimeout):
        asyncio.run(p.poll(probe, predicate=lambda v: False,
                           deadline_s=0.05))


def test_circuit_breaker_state_machine():
    clock = {"t": 0.0}
    cb = retry.CircuitBreaker(failure_threshold=2, reset_timeout_s=1.0,
                              clock=lambda: clock["t"])
    assert cb.available("r1")
    cb.record_failure("r1")
    assert cb.available("r1")  # below threshold
    cb.record_failure("r1")
    assert not cb.available("r1")  # OPEN
    assert cb.state("r1") == "OPEN"
    clock["t"] = 1.5
    assert cb.available("r1")  # HALF_OPEN probe allowed
    cb.record_failure("r1")  # probe failed -> re-OPEN for a new window
    assert not cb.available("r1")
    clock["t"] = 3.0
    assert cb.available("r1")
    cb.record_success("r1")  # probe succeeded -> CLOSED
    assert cb.state("r1") == "CLOSED"
    cb.record_failure("r1")
    assert cb.available("r1")  # success reset the consecutive count


# ---------------------------------------------------------------------------
# injector over real connections
# ---------------------------------------------------------------------------


@pytest.fixture()
def rpc_pair():
    lt = rpc.EventLoopThread(name="fi-test-io")
    seen = {"bump": 0}

    async def h_echo(conn, payload):
        return {"v": payload["v"]}

    def h_bump(conn, payload):  # sync notification fast path
        seen["bump"] += 1

    server = rpc.Server({"echo": h_echo, "bump": h_bump}, name="srv")
    port = lt.run(server.start("127.0.0.1", 0))
    conn = lt.run(rpc.connect("127.0.0.1", port, {}, name="cli"))
    try:
        yield lt, conn, seen
    finally:
        try:
            lt.run(conn.close(), timeout=5)
            lt.run(server.stop(), timeout=5)
        except Exception:
            pass
        lt.stop()


def test_drop_healed_by_retry(rpc_pair):
    lt, conn, _ = rpc_pair
    fi = rpc.get_fault_injector()
    fi.install("drop", peer="cli", method="echo", direction="send",
               max_matches=1)
    policy = retry.RetryPolicy(max_attempts=3, base_delay_s=0.02,
                               jitter=0.0)
    out = lt.run(policy.execute(
        lambda: conn.call("echo", {"v": 41}),
        timeout_per_attempt=0.5))
    assert out == {"v": 41}
    assert policy.total_retries == 1
    assert fi.stats["drop"] == 1


def test_delay_injection(rpc_pair):
    lt, conn, _ = rpc_pair
    fi = rpc.get_fault_injector()
    fi.install("delay", peer="cli", method="echo", delay_s=0.3)
    start = time.monotonic()
    assert lt.run(conn.call("echo", {"v": 1}, timeout=5)) == {"v": 1}
    assert time.monotonic() - start >= 0.25
    fi.clear()
    start = time.monotonic()
    assert lt.run(conn.call("echo", {"v": 2}, timeout=5)) == {"v": 2}
    assert time.monotonic() - start < 0.25


def test_duplicate_injection(rpc_pair):
    lt, conn, seen = rpc_pair
    fi = rpc.get_fault_injector()
    fi.install("duplicate", peer="cli", method="bump", direction="send")
    lt.run(conn.notify("bump", {}))
    deadline = time.monotonic() + 5
    while seen["bump"] < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert seen["bump"] == 2  # one send, two deliveries


def test_partition_send_raises_unsent(rpc_pair):
    lt, conn, _ = rpc_pair
    fi = rpc.get_fault_injector()
    rid = fi.install("partition", peer="cli", direction="send")
    with pytest.raises(rpc.ConnectionLost) as ei:
        lt.run(conn.call("echo", {"v": 1}))
    assert ei.value.sent is False  # provably never hit the socket
    assert not conn.closed  # the transport itself is intact
    fi.clear(rid)
    assert lt.run(conn.call("echo", {"v": 2}, timeout=5)) == {"v": 2}


def test_partition_recv_drops_inbound(rpc_pair):
    lt, conn, _ = rpc_pair
    fi = rpc.get_fault_injector()
    # One-way partition: requests go out, responses are eaten.
    rid = fi.install("partition", peer="cli", direction="recv")
    with pytest.raises(asyncio.TimeoutError):
        lt.run(conn.call("echo", {"v": 1}, timeout=0.3))
    fi.clear(rid)
    assert lt.run(conn.call("echo", {"v": 2}, timeout=5)) == {"v": 2}


def test_rules_bypass_sync_notify_fast_path(rpc_pair):
    lt, conn, seen = rpc_pair
    fi = rpc.get_fault_injector()
    fi.install("drop", peer="cli", method="bump", direction="send")
    # try_notify_sync must refuse (loop path owns fault application),
    # and the loop path then drops the frame.
    assert conn.try_notify_sync("bump", {}) is False
    lt.run(conn.notify("bump", {}))
    time.sleep(0.2)
    assert seen["bump"] == 0


# ---------------------------------------------------------------------------
# partition during an actor call, healed by the unified policy
# ---------------------------------------------------------------------------


@pytest.fixture()
def chaos_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0, system_config={
        # Deterministic, partition-outlasting envelope for the test.
        "rpc_retry_max_attempts": 8,
        "rpc_retry_jitter": 0.0,
        "rpc_retry_base_delay_s": 0.05,
    })
    try:
        yield ray_tpu
    finally:
        if rpc._fault_injector is not None:
            rpc._fault_injector.reset()
        ray_tpu.shutdown()


def test_partition_during_actor_call_heals(chaos_cluster):
    ray_tpu = chaos_cluster
    from ray_tpu.core.object_ref import get_core_worker

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, k):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(1), timeout=60) == 1  # conn warm
    cw = get_core_worker()
    retries_before = cw._rpc_retry.total_retries

    fi = rpc.get_fault_injector()
    # Partition the driver away from every worker push channel: frames
    # fail with sent=False, so the unified policy retries in place.
    rid = fi.install("partition", peer="peer-*", method="push_tasks",
                     direction="send")
    ref = c.bump.remote(41)
    time.sleep(0.5)  # a few failed+backed-off attempts land here
    fi.clear(rid)
    assert ray_tpu.get(ref, timeout=60) == 42  # healed, exactly-once
    assert cw._rpc_retry.total_retries > retries_before


def test_partition_during_normal_task_heals(chaos_cluster):
    ray_tpu = chaos_cluster
    from ray_tpu.core.object_ref import get_core_worker

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 1), timeout=60) == 2  # lease warm
    cw = get_core_worker()
    retries_before = cw._rpc_retry.total_retries
    fi = rpc.get_fault_injector()
    rid = fi.install("partition", peer="peer-*", method="push_tasks",
                     direction="send")
    refs = [add.remote(i, 10) for i in range(4)]
    time.sleep(0.4)
    fi.clear(rid)
    assert ray_tpu.get(refs, timeout=60) == [10, 11, 12, 13]
    assert cw._rpc_retry.total_retries > retries_before


# ---------------------------------------------------------------------------
# GCS node-death grace window
# ---------------------------------------------------------------------------


class _FakeAgentConn:
    """Stands in for a node agent's rpc.Connection on the head side."""

    def __init__(self):
        self.on_close = None
        self.closed = False
        self.state = {}

    def notify_forget(self, method, payload=None):
        pass

    def drop(self):
        """Simulate the TCP-level close a partition produces."""
        self.closed = True
        if self.on_close:
            self.on_close(self)


class _FakeShm:
    def contains(self, object_id):
        return False

    def delete(self, object_id):
        pass

    def pin(self, object_id):
        pass

    def unpin(self, object_id):
        pass

    def mark_sealed(self, object_id, size):
        pass

    def cleanup(self):
        pass


def test_gcs_grace_window_spares_briefly_partitioned_node(tmp_path):
    from ray_tpu.core.config import Config
    from ray_tpu.core.gcs import HeadService
    from ray_tpu.core.ids import NodeID

    os.makedirs(tmp_path / "logs", exist_ok=True)

    async def scenario():
        config = Config()
        config.gcs_node_death_grace_s = 0.5
        config.memory_monitor_enabled = False
        head = HeadService(config, _FakeShm(), str(tmp_path))
        head.attach(0)
        try:
            conn = _FakeAgentConn()
            reply = await head.h_register_node(conn, {
                "host": "127.0.0.1", "port": 12345,
                "resources": {"CPU": 2.0},
            })
            assert reply["ok"]
            node_id = NodeID.from_hex(reply["node_id"])
            assert head.nodes_info[node_id].state == "ALIVE"

            # Health channel drops (partition): node goes SUSPECT, not
            # DEAD, and stays schedulable in the grace window.
            conn.drop()
            assert head.nodes_info[node_id].state == "SUSPECT"
            assert node_id in head.scheduler.nodes
            await asyncio.sleep(0.2)  # sub-grace partition
            assert head.nodes_info[node_id].state == "SUSPECT"

            # Agent reconnects inside the window carrying its node id:
            # reattached under the SAME identity, no node churn.
            conn2 = _FakeAgentConn()
            reply2 = await head.h_register_node(conn2, {
                "host": "127.0.0.1", "port": 12345,
                "resources": {"CPU": 2.0},
                "node_id": reply["node_id"],
            })
            assert reply2["node_id"] == reply["node_id"]
            assert head.nodes_info[node_id].state == "ALIVE"
            assert len(head.nodes_info) == 1
            # The grace timer must have been disarmed: well past the
            # original window the node is still alive.
            await asyncio.sleep(0.7)
            assert head.nodes_info[node_id].state == "ALIVE"

            # A partition that OUTLASTS the grace window is a real
            # death.
            conn2.drop()
            assert head.nodes_info[node_id].state == "SUSPECT"
            await asyncio.sleep(0.8)
            assert head.nodes_info[node_id].state == "DEAD"

            # Too-late reconnect: the head mints a fresh node.
            conn3 = _FakeAgentConn()
            reply3 = await head.h_register_node(conn3, {
                "host": "127.0.0.1", "port": 12345,
                "resources": {"CPU": 2.0},
                "node_id": reply["node_id"],
            })
            assert reply3["ok"]
            assert reply3["node_id"] != reply["node_id"]
        finally:
            await head.shutdown()

    asyncio.run(scenario())


def test_gcs_zero_grace_restores_instant_death(tmp_path):
    from ray_tpu.core.config import Config
    from ray_tpu.core.gcs import HeadService
    from ray_tpu.core.ids import NodeID

    os.makedirs(tmp_path / "logs", exist_ok=True)

    async def scenario():
        config = Config()
        config.gcs_node_death_grace_s = 0.0
        config.memory_monitor_enabled = False
        head = HeadService(config, _FakeShm(), str(tmp_path))
        head.attach(0)
        try:
            conn = _FakeAgentConn()
            reply = await head.h_register_node(conn, {
                "host": "127.0.0.1", "port": 12345,
                "resources": {"CPU": 1.0},
            })
            node_id = NodeID.from_hex(reply["node_id"])
            conn.drop()
            assert head.nodes_info[node_id].state == "DEAD"
        finally:
            await head.shutdown()

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# chaos killers (util/chaos.py)
# ---------------------------------------------------------------------------


def test_killer_deadline_stops_without_candidates():
    # No cluster: list_actors would fail, but the deadline fires before
    # the first poll tick needs results.
    from ray_tpu.util.chaos import ActorKiller, WorkerKiller

    async def scenario():
        killer = ActorKiller(kill_interval_s=10.0, max_kills=3,
                             max_duration_s=0.1)
        start = time.monotonic()
        killed = await killer.run()
        assert killed == 0
        assert time.monotonic() - start < 5.0
        wk = WorkerKiller(kill_interval_s=10.0, max_kills=3,
                          max_duration_s=0.1)
        assert await wk.run() == 0
        assert await wk.get_errors() == 0

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# soak variants (excluded from tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_soak_flapping_partition_many_tasks(chaos_cluster):
    """Partition windows flap while a task wave runs; every task still
    completes exactly once."""
    ray_tpu = chaos_cluster

    @ray_tpu.remote
    def work(i):
        time.sleep(0.01)
        return i * 2

    fi = rpc.get_fault_injector()
    stop = threading.Event()

    def flapper():
        while not stop.is_set():
            rid = fi.install("partition", peer="peer-*",
                             method="push_tasks", direction="send")
            time.sleep(0.15)
            fi.clear(rid)
            time.sleep(0.35)

    t = threading.Thread(target=flapper, daemon=True)
    t.start()
    try:
        refs = [work.options(max_retries=5).remote(i) for i in range(60)]
        results = ray_tpu.get(refs, timeout=300)
    finally:
        stop.set()
        t.join(timeout=5)
        fi.reset()
    assert results == [i * 2 for i in range(60)]


@pytest.mark.slow
def test_soak_duplicated_replies_are_idempotent(chaos_cluster):
    """Duplicate every task_done delivery: the reply ledger must absorb
    replays without double-completing or corrupting queue accounting."""
    ray_tpu = chaos_cluster

    @ray_tpu.remote
    def work(i):
        return i + 100

    fi = rpc.get_fault_injector()
    fi.install("duplicate", peer="peer-*", method="task_done",
               direction="recv")
    try:
        refs = [work.remote(i) for i in range(40)]
        assert ray_tpu.get(refs, timeout=300) == [
            i + 100 for i in range(40)]
    finally:
        fi.reset()
