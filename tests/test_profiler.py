"""Live profiling plane: sampler units (folded aggregation, bounded
memory, attribution, continuous-mode overhead bound) and the cluster
e2e lanes (on-demand capture of a busy worker with task attribution,
killed-worker flight-ring shipping).

Unit tests run first — they must see NO cluster (the timeline fallback
and the no-core-worker shipping paths are part of what they test); the
module-scoped cluster fixture only spins up for the e2e half.
"""

import json
import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import flight_recorder as fr
from ray_tpu.util import profiler, telemetry


def _wait_for(predicate, timeout=30.0, interval=0.05, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


class _BusyThread(threading.Thread):
    """A thread provably inside ``_busy_spin_marker`` while running."""

    def __init__(self):
        super().__init__(daemon=True, name="busy-probe")
        self.stop = threading.Event()

    def _busy_spin_marker(self):
        x = 0
        while not self.stop.is_set():
            x += 1
        return x

    def run(self):
        self._busy_spin_marker()


@pytest.fixture
def busy_thread():
    t = _BusyThread()
    t.start()
    yield t
    t.stop.set()
    t.join(timeout=5)


# ---------------------------------------------------------------------------
# sampler units
# ---------------------------------------------------------------------------

def test_capture_folded_aggregation(busy_thread):
    out = profiler.capture(duration_s=0.4, hz=200)
    assert out["samples"] > 0
    assert out["sweeps"] > 10
    # Every sample landed in exactly one folded stack.
    assert sum(out["folded"].values()) == out["samples"]
    # The busy thread's frames are visible, rooted at its thread lane.
    busy = [s for s in out["folded"] if "_busy_spin_marker" in s]
    assert busy, f"busy frames missing from {list(out['folded'])[:5]}"
    assert all(s.startswith("thread:busy-probe") for s in busy)
    # The busy loop dominates its own thread's samples.
    assert max(out["folded"][s] for s in busy) > out["sweeps"] * 0.5
    # folded text round-trips as `stack count` lines.
    text = profiler.folded_text(out["folded"])
    first = text.splitlines()[0]
    stack, count = first.rsplit(" ", 1)
    assert int(count) == max(out["folded"].values())


def test_task_attribution_buckets():
    ready = threading.Event()
    stop = threading.Event()

    def attributed_work():
        token = profiler.push_thread_context(
            task="abc123def4567890", name="my_busy_task")
        ready.set()
        try:
            while not stop.is_set():
                pass
        finally:
            profiler.pop_thread_context(token)

    t = threading.Thread(target=attributed_work, daemon=True)
    t.start()
    ready.wait(5)
    try:
        out = profiler.capture(duration_s=0.3, hz=200)
    finally:
        stop.set()
        t.join(timeout=5)
    # Sampled stacks of the attributed thread root at task:<name> ...
    task_stacks = [s for s in out["folded"]
                   if s.startswith("task:my_busy_task")]
    assert task_stacks
    assert any("attributed_work" in s for s in task_stacks)
    # ... and the per-task bucket counts its samples.
    bucket = out["tasks"]["abc123def4567890"]
    assert bucket["name"] == "my_busy_task"
    assert bucket["samples"] == sum(out["folded"][s]
                                    for s in task_stacks)


def test_pop_thread_context_token_order_independent():
    a = profiler.push_thread_context(task="a", name="a")
    b = profiler.push_thread_context(task="b", name="b")
    # Interleaved-coroutine shape: the FIRST pusher pops first.
    profiler.pop_thread_context(a)
    assert profiler.current_thread_context() is b
    profiler.pop_thread_context(b)
    assert profiler.current_thread_context() is None
    # Double-pop is benign.
    profiler.pop_thread_context(b)


def test_bounded_unique_stacks(monkeypatch):
    monkeypatch.setattr(profiler, "MAX_UNIQUE_STACKS", 4)
    counts = {}
    for i in range(10):
        profiler._add(counts, f"stack-{i}")
    # 4 distinct keys + the overflow bucket, never more.
    assert len(counts) == 5
    assert counts[profiler.OVERFLOW_KEY] == 6
    # Existing keys keep counting past the cap.
    profiler._add(counts, "stack-0")
    assert counts["stack-0"] == 2


def test_flamegraph_html_self_contained():
    folded = {"thread:main;a.py:f;a.py:g": 7,
              "task:t;b.py:h": 3}
    html = profiler.flamegraph_html(folded, title="unit test")
    assert "<script>" in html and "</html>" in html
    for frame in ("a.py:f", "a.py:g", "b.py:h", "task:t"):
        assert frame in html
    assert "unit test" in html
    # Self-contained: no external asset fetches.
    assert "http://" not in html and "https://" not in html
    # The embedded tree is valid JSON with the right total.
    data = html.split("var DATA=", 1)[1].split(";\n", 1)[0]
    tree = json.loads(data)
    assert tree["v"] == 10


def test_merge_folded_roots_per_source():
    merged = profiler.merge_folded([
        {"source": "worker:aa", "folded": {"thread:x;f": 2}},
        {"source": "head", "folded": {"thread:x;f": 5}},
    ])
    assert merged == {"worker:aa;thread:x;f": 2, "head;thread:x;f": 5}


def test_continuous_sampler_overhead_bound(tmp_path, busy_thread):
    """The always-on mode's acceptance bar: measured overhead on a busy
    process stays under the configured 2% bound, snapshots land on
    disk, and the overhead gauge + profile:<pid> timeline lane are
    published."""
    sampler = profiler.ContinuousSampler(
        hz=10.0, snapshot_interval_s=0.3, out_dir=str(tmp_path),
        max_overhead=0.02)
    sampler.start()
    try:
        _wait_for(lambda: sampler.total_samples > 0, timeout=10,
                  desc="a continuous snapshot window")
        assert sampler.last_overhead_ratio <= 0.02, (
            f"continuous sampler overhead {sampler.last_overhead_ratio:.4f}"
            " exceeds the 2% bound")
        assert not sampler.throttled
        _wait_for(lambda: os.path.exists(sampler.snapshot_path),
                  timeout=10, desc="the folded snapshot file")
    finally:
        sampler.stop()
        sampler.join(timeout=5)
    text = open(sampler.snapshot_path).read()
    assert text.strip(), "snapshot file is empty"
    stack, count = text.splitlines()[0].rsplit(" ", 1)
    assert int(count) > 0 and ";" in stack
    # Overhead gauge carries this process's tag.
    gauge = telemetry.metric("ray_tpu_profiler_overhead_ratio")
    assert any(("proc", telemetry.proc_tag()) in k
               for k in gauge._values)
    # The profile:<pid> lane rides the telemetry event stream.
    lane = f"profile:{os.getpid()}"
    assert any(ev["cat"] == lane
               for ev in telemetry.local_timeline_events())


def test_timeline_merges_profile_lane_without_cluster():
    """No cluster attached: the timeline export falls back to the local
    telemetry buffer, so the continuous sampler's lane still renders."""
    telemetry.event(f"profile:{os.getpid()}", "window", dur=0.5,
                    args={"samples": 3})
    from ray_tpu.util.timeline import timeline

    trace = timeline(events=[], include_flight=False)
    assert any(ev["tid"] == f"profile:{os.getpid()}" for ev in trace)


def test_maybe_start_continuous_gated_by_config():
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old = cfg.profiler_continuous_enabled
    try:
        cfg.profiler_continuous_enabled = False
        assert profiler.maybe_start_continuous() is None
        cfg.profiler_continuous_enabled = True
        sampler = profiler.maybe_start_continuous()
        assert sampler is not None and sampler.is_alive()
        # Idempotent: a second call hands back the same thread.
        assert profiler.maybe_start_continuous() is sampler
    finally:
        cfg.profiler_continuous_enabled = old
        profiler.stop_continuous_for_testing()


def test_error_event_arms_ring_ship():
    fr.reset_for_testing(capacity=32)
    fr.record("sched", "lease_wait", severity="warn", reason="x")
    assert not fr._ship_pending, "warn must not arm the ship"
    fr.record("gcs", "node_dead", severity="error", node="deadbeef")
    assert fr._ship_pending, "error must arm the ship"
    # No core worker here: ship_ring_now reports failure, never raises.
    assert fr.ship_ring_now() is False
    fr.reset_for_testing()


# ---------------------------------------------------------------------------
# e2e: on-demand capture + attribution, ring shipping past SIGKILL
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def profile_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_on_demand_capture_attributes_busy_task(profile_cluster,
                                                tmp_path):
    """The acceptance lane: profile the worker running a busy task and
    get folded stacks whose top frames are attributed to that task,
    plus flamegraph/folded outputs on disk."""

    @ray_tpu.remote
    def busy_burn(seconds):
        t0 = time.monotonic()
        x = 0
        while time.monotonic() - t0 < seconds:
            x += 1
        return x

    ref = busy_burn.remote(8.0)
    task_hex = ref.id.task_id().hex()

    from ray_tpu.util import state as ust

    def running_with_worker():
        rows = ust.list_tasks(
            filters=[("task_id", "contains", task_hex)])
        return any(r["state"] == "RUNNING" and r.get("worker_id")
                   for r in rows)

    _wait_for(running_with_worker, desc="busy task RUNNING at the head")

    reply = profiler.capture_cluster("task", task_hex,
                                     duration_s=1.5, hz=100)
    assert not reply.get("error"), reply
    (entry,) = reply["entries"]
    assert entry["source"].startswith("worker:")
    assert entry["samples"] > 0
    # Top frames belong to the running task: the stacks rooted at
    # task:busy_burn carry the task's code and dominate the executor
    # thread across the window (parked I/O threads also produce stable
    # stacks, so the claim is about the task lane, not a global max).
    task_stacks = {s: c for s, c in entry["folded"].items()
                   if s.startswith("task:busy_burn")}
    assert task_stacks, sorted(entry["folded"])[:8]
    assert any("busy_burn" in s for s in task_stacks)
    assert max(task_stacks.values()) > entry["sweeps"] * 0.5
    # Attribution bucket keyed by the task id.
    bucket = entry["tasks"].get(task_hex[:16])
    assert bucket and bucket["samples"] > 0
    assert bucket["name"] == "busy_burn"

    # `ray_tpu profile worker <id>` path: same worker, targeted by id.
    reply2 = profiler.capture_cluster("worker", entry["worker_id"],
                                      duration_s=0.5, hz=50)
    assert not reply2.get("error"), reply2
    assert reply2["entries"][0]["worker_id"] == entry["worker_id"]

    # File outputs: folded text + self-contained flamegraph HTML.
    out = str(tmp_path / "prof")
    manifest = profiler.write_profile_outputs(reply, out)
    assert manifest["samples"] == entry["samples"]
    assert os.path.exists(manifest["flamegraph"])
    html = open(manifest["flamegraph"]).read()
    assert "busy_burn" in html
    folded_files = [n for n in os.listdir(out) if n.endswith(".folded")]
    assert folded_files
    assert ray_tpu.get(ref, timeout=60) > 0


def test_profile_cluster_all_covers_head_and_workers(profile_cluster):
    @ray_tpu.remote
    def touch():
        return os.getpid()

    ray_tpu.get(touch.remote())
    reply = profiler.capture_cluster("all", duration_s=0.5, hz=50)
    sources = {e["source"] for e in reply["entries"]
               if not e.get("error")}
    assert "head" in sources
    assert any(s.startswith("worker:") for s in sources)
    for e in reply["entries"]:
        if not e.get("error"):
            assert e["samples"] >= 0
            assert "folded" in e


def test_profile_capture_cluster_unknown_target(profile_cluster):
    reply = profiler.capture_cluster("worker", "ffffffffffff",
                                     duration_s=0.2)
    assert reply.get("error")
    assert reply["entries"] == []


def test_ring_ships_on_error_via_push_throttle(profile_cluster):
    """Driver-side: a severity>=error event arms the ship, the next
    metrics push delivers the ring tail to the head KV."""
    fr.record("gcs", "node_dead", severity="error",
              node="ringship-probe")
    from ray_tpu.util import metrics as um

    um.flush_metrics()  # forces the push; the hook rides it

    from ray_tpu.core.object_ref import get_core_worker
    from ray_tpu.util.state import _call

    wid = get_core_worker().worker_id.hex()

    def shipped():
        reply = _call("kv_get", {"ns": "flightring",
                                 "key": f"fr:{wid}".encode()})
        blob = reply.get("value")
        if not blob:
            return False
        data = json.loads(bytes(blob).decode())
        return any(e.get("event") == "node_dead"
                   and (e.get("tags") or {}).get("node")
                   == "ringship-probe" for e in data["events"])

    _wait_for(shipped, timeout=15, desc="the ring tail in the head KV")

    # A LIVE driver's shipped copy must not masquerade as a dead
    # worker in dumps (drivers splice themselves in client-side).
    from ray_tpu.util import debug as udebug

    dump = udebug.cluster_debug_dump(include_stacks=False)
    assert not any(e.get("shipped") and e.get("worker_id") == wid
                   for e in dump["entries"])


def test_killed_worker_ring_survives_in_debug_dump(profile_cluster):
    """A SIGKILL'd worker leaves evidence: its shipped ring shows up in
    debug_dump_cluster as a shipped:worker:* entry."""

    @ray_tpu.remote(max_retries=0)
    def doomed():
        from ray_tpu.util import flight_recorder

        flight_recorder.record(
            "debug", "postmortem", severity="error",
            reason="pre-SIGKILL evidence")
        # Deterministic ship (the throttled path races a SIGKILL by
        # design); then die hard — no flush, no atexit.
        assert flight_recorder.ship_ring_now()
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(doomed.remote(), timeout=60)

    from ray_tpu.util import debug as udebug

    def killed_ring_visible():
        dump = udebug.cluster_debug_dump(include_stacks=False)
        for entry in dump["entries"]:
            if not entry.get("shipped"):
                continue
            for ev in entry.get("events", []):
                tags = ev.get("tags") or {}
                if tags.get("reason") == "pre-SIGKILL evidence":
                    return True
        return False

    _wait_for(killed_ring_visible, timeout=20,
              desc="the killed worker's shipped ring in the dump")
