"""Arrow-backed blocks + push-based shuffle.

Reference: python/ray/data/_internal/arrow_block.py (Arrow as the
columnar interchange format) and
_internal/planner/exchange/push_based_shuffle_task_scheduler.py."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data.arrow_block import (
    ArrowBlockAccessor,
    block_to_arrow,
    is_arrow_block,
)
from ray_tpu.data.block import BlockAccessor, concat_blocks

pa = pytest.importorskip("pyarrow")


def test_accessor_dispatch():
    table = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    acc = BlockAccessor(table)
    assert isinstance(acc, ArrowBlockAccessor)
    assert acc.num_rows() == 3
    assert acc.schema() == {"a": "int64", "b": "string"}
    numpy_acc = BlockAccessor({"a": np.arange(3)})
    assert not isinstance(numpy_acc, ArrowBlockAccessor)


def test_arrow_slice_is_zero_copy():
    table = pa.table({"a": np.arange(1000)})
    acc = BlockAccessor(table)
    part = acc.slice(100, 200)
    assert is_arrow_block(part)
    assert part.num_rows == 100
    # Zero copy: the slice shares the parent's buffers.
    assert part["a"].chunks[0].buffers()[1].address == \
        table["a"].chunks[0].buffers()[1].address


def test_arrow_concat_and_rows():
    t1 = pa.table({"a": [1, 2]})
    t2 = pa.table({"a": [3]})
    out = concat_blocks([t1, t2])
    assert is_arrow_block(out)
    assert BlockAccessor(out).num_rows() == 3
    assert [r["a"] for r in BlockAccessor(out).iter_rows()] == [1, 2, 3]
    # Mixed arrow + numpy normalizes to numpy.
    mixed = concat_blocks([t1, {"a": np.array([9])}])
    assert type(mixed) is dict
    assert list(mixed["a"]) == [1, 2, 9]


def test_parquet_roundtrip_stays_arrow(ray_start, tmp_path):
    import pyarrow.parquet as pq

    src = str(tmp_path / "in")
    os.makedirs(src)
    pq.write_table(
        pa.table({"x": np.arange(100, dtype=np.int64),
                  "y": np.arange(100, dtype=np.float64) * 0.5}),
        os.path.join(src, "f.parquet"))

    from ray_tpu import data

    ds = data.read_parquet(src)
    # Blocks are Arrow tables end-to-end (no row materialization).
    block = ray_tpu.get(next(iter(ds._execute()))[0], timeout=120)
    assert is_arrow_block(block)
    out_dir = str(tmp_path / "out")
    files = ds.write_parquet(out_dir)
    assert files
    back = pq.read_table(out_dir)
    assert back.num_rows == 100
    assert back.sort_by("x")["y"][10].as_py() == 5.0


def test_arrow_blocks_through_map_and_iter(ray_start, tmp_path):
    import pyarrow.parquet as pq

    path = str(tmp_path / "m.parquet")
    pq.write_table(pa.table({"v": np.arange(50, dtype=np.int64)}), path)
    from ray_tpu import data

    ds = data.read_parquet(path).map_batches(
        lambda b: {"v": b["v"] * 2})
    vals = sorted(r["v"] for r in ds.take_all())
    assert vals == [2 * i for i in range(50)]


def test_pyarrow_batch_format(ray_start):
    from ray_tpu import data

    ds = data.range(10)
    batches = list(ds.iter_batches(batch_size=None,
                                   batch_format="pyarrow"))
    assert all(isinstance(b, pa.Table) for b in batches)


def test_push_based_shuffle_correct(ray_start):
    from ray_tpu import data

    os.environ["RAY_TPU_SHUFFLE_STRATEGY"] = "push"
    try:
        ds = data.range(2000, parallelism=8).random_shuffle(seed=7)
        vals = sorted(ds.take_all())
        assert vals == list(range(2000))
        # Determinism under a fixed seed.
        again = data.range(2000, parallelism=8).random_shuffle(seed=7)
        assert ds.take_all() == again.take_all()
    finally:
        os.environ.pop("RAY_TPU_SHUFFLE_STRATEGY", None)


def test_push_shuffle_matches_pull(ray_start):
    from ray_tpu import data

    os.environ["RAY_TPU_SHUFFLE_STRATEGY"] = "pull"
    try:
        pull = sorted(
            data.range(500, parallelism=4).random_shuffle().take_all())
    finally:
        os.environ.pop("RAY_TPU_SHUFFLE_STRATEGY", None)
    os.environ["RAY_TPU_SHUFFLE_STRATEGY"] = "push"
    try:
        push = sorted(
            data.range(500, parallelism=4).random_shuffle().take_all())
    finally:
        os.environ.pop("RAY_TPU_SHUFFLE_STRATEGY", None)
    assert pull == push == list(range(500))
