"""E2E debug plane: cluster-wide `debug dump` bundles under fault
injection, and the `why is it stuck` explainer on a task blocked by an
unplaceable (busy) resource."""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.core import rpc
from ray_tpu.util import flight_recorder
from ray_tpu.util.cluster_utils import Cluster


@pytest.fixture
def debug_cluster():
    """Two logical nodes (head + one with a custom ``n2`` resource) so
    the dump provably covers more than one node's workers."""
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "num_tpus": 0})
    cluster.add_node(num_cpus=2, resources={"n2": 1})
    yield cluster
    injector = rpc._fault_injector
    if injector is not None:
        injector.reset()
    rpc.reset_fault_injector()
    cluster.shutdown()


def _wait_for(predicate, timeout=30.0, interval=0.1, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def test_debug_dump_bundle_under_fault_injection(debug_cluster,
                                                 tmp_path):
    # Activate the fault plane: delay every kv_* control frame a hair.
    # The injected matches land in the head process's flight ring,
    # proving the dump captures fault-plane evidence.
    rpc.get_fault_injector().install(
        "delay", method="kv_*", delay_s=0.002)

    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote(resources={"n2": 1})
    def g(x):
        return x * 2

    assert ray_tpu.get([f.remote(1), f.remote(2)]) == [2, 3]
    assert ray_tpu.get(g.remote(3)) == 6

    from ray_tpu.util import debug as udebug

    out = str(tmp_path / "bundle")
    manifest = udebug.write_debug_bundle(out)

    # Every process contributed: the head plus at least one worker per
    # logical node (f ran on the head node, g's resource pinned it to
    # the second node).
    assert "head" in manifest["sources"]
    workers = [s for s in manifest["sources"]
               if s.startswith("worker:")]
    assert len(workers) >= 2
    cluster_nodes = {n["node_id"] for n in debug_cluster.list_nodes()}
    assert cluster_nodes <= set(manifest["nodes"])
    assert not manifest["errors"], manifest["errors"]

    # Rings: parseable, and worker nodes both represented.
    rings_dir = os.path.join(out, "rings")
    ring_nodes = set()
    for name in os.listdir(rings_dir):
        entry = json.loads(open(os.path.join(rings_dir, name)).read())
        if entry.get("node_id"):
            ring_nodes.add(entry["node_id"])
    assert cluster_nodes <= ring_nodes

    # Stacks: one file per source, each naming at least one thread.
    stacks_dir = os.path.join(out, "stacks")
    stack_files = os.listdir(stacks_dir)
    assert len(stack_files) == len(manifest["sources"])
    for name in stack_files:
        text = open(os.path.join(stacks_dir, name)).read()
        assert "--- " in text, f"{name} has no thread stacks"

    # The head's ring holds the causal evidence: lease grants, node
    # registration, and the injected faults.
    head_ring = json.loads(
        open(os.path.join(rings_dir, "head.json")).read())
    events = {(e["subsystem"], e["event"])
              for e in head_ring["events"]}
    assert ("sched", "lease_granted") in events
    assert ("gcs", "node_alive") in events
    assert ("rpc", "fault_injected") in events

    # State tables + sched state + metrics + timeline all landed.
    for rel in ("state/nodes.json", "state/workers.json",
                "state/tasks.json", "state/objects.json",
                "sched_state.json", "metrics.json", "timeline.json",
                "manifest.json"):
        assert os.path.exists(os.path.join(out, rel)), rel
    workers_tbl = json.loads(
        open(os.path.join(out, "state", "workers.json")).read())
    assert len(workers_tbl) >= 2

    # Live profiling plane: the bundle carries a short cluster-wide
    # sampling capture — per-source folded stacks + a merged flamegraph.
    assert manifest.get("profile"), "bundle missing the profile section"
    assert "head" in manifest["profile"]["sources"]
    prof_dir = os.path.join(out, "profile")
    assert os.path.exists(os.path.join(prof_dir, "flamegraph.html"))
    folded_files = [n for n in os.listdir(prof_dir)
                    if n.endswith(".folded")]
    assert len(folded_files) >= len(manifest["profile"]["sources"])


def test_debug_stacks_cluster_wide(debug_cluster):
    @ray_tpu.remote
    def f():
        return os.getpid()

    ray_tpu.get(f.remote())
    from ray_tpu.util import debug as udebug

    stacks = udebug.cluster_stacks()
    assert "head" in stacks
    assert any(s.startswith("worker:") for s in stacks)
    for source, threads in stacks.items():
        assert threads, f"{source} returned no threads"


def test_why_task_blocked_on_busy_resource(debug_cluster, tmp_path):
    flag = str(tmp_path / "release")

    @ray_tpu.remote(resources={"n2": 1})
    def hold(path):
        while not os.path.exists(path):
            time.sleep(0.05)
        return "done"

    @ray_tpu.remote(resources={"n2": 1})
    def blocked():
        return 41

    r1 = hold.remote(flag)
    # Wait until hold actually occupies the resource.
    _wait_for(lambda: ray_tpu.available_resources().get("n2", 0) == 0,
              desc="hold() to take the n2 resource")
    r2 = blocked.remote()
    task_hex = r2.id.task_id().hex()

    from ray_tpu.util.state import _call

    def lease_pending():
        state = _call("debug_sched_state")
        return any(p["task_id"] == task_hex and p["wait_reason"]
                   for p in state["pending"])

    _wait_for(lease_pending, desc="blocked()'s lease to park with a "
                                  "wait reason")

    from ray_tpu.util import debug as udebug

    text = udebug.why("task", task_hex[:16])
    assert "PENDING" in text
    assert "waiting for resources" in text
    assert "n2" in text
    assert "last scheduler decision" in text

    # The causal walk also explains the not-yet-produced return object.
    otext = udebug.why("object", r2.id.hex())
    assert "NOT sealed" in otext
    assert "producing task" in otext

    # Release and confirm nothing was harmed by the introspection.
    with open(flag, "w") as f:
        f.write("go")
    assert ray_tpu.get([r1, r2], timeout=60) == ["done", 41]

    # After completion the explainer reports the terminal state (the
    # worker's task-event buffer flushes on a ~1s cadence).
    from ray_tpu.util import state as ust

    _wait_for(lambda: any(
        e["state"] == "FINISHED" for e in
        ust.list_tasks(filters=[("task_id", "contains", task_hex)])),
        desc="the FINISHED task event to reach the head")
    done_text = udebug.why("task", task_hex[:16])
    assert "FINISHED" in done_text


def test_why_placement_group_unplaceable(debug_cluster):
    """`ray_tpu debug why placement-group <id>` walks bundle placement
    and pending-wait evidence for a PG the cluster cannot place."""
    pg = ray_tpu.placement_group([{"CPU": 1}, {"n2": 64}],
                                 strategy="PACK")
    try:
        from ray_tpu.util.state import _call

        def pg_visible():
            return any(p["pg_id"] == pg.id_hex
                       for p in _call("debug_sched_state")["pgs"])

        _wait_for(pg_visible, desc="the PG in the scheduler state")

        from ray_tpu.util import debug as udebug

        text = udebug.why("placement-group", pg.id_hex[:16])
        assert "placement group" in text
        # The oversized n2 bundle cannot place: the walk names the
        # shortfall and the cluster's availability.
        assert "bundle(s) unplaced" in text
        assert "cluster:" in text

        # Unknown ids come back honest.
        missing = udebug.why("placement-group", "f" * 16)
        assert "no placement group" in missing
    finally:
        ray_tpu.remove_placement_group(pg)


def test_postmortem_written_on_worker_crash(debug_cluster):
    """A worker dying to a hard crash leaves a postmortem file in the
    session log dir (the crash handler installed by worker_main)."""
    session_dir = os.environ.get("RAY_TPU_SESSION_DIR")
    assert session_dir

    @ray_tpu.remote(max_retries=0)
    def crash():
        # Raising through the worker's executor is task failure, not a
        # process crash; kill the interpreter from a side thread with a
        # real unhandled exception instead.
        import threading

        def boom():
            raise RuntimeError("synthetic worker crash")

        t = threading.Thread(target=boom)
        t.start()
        t.join()
        return os.getpid()

    ray_tpu.get(crash.remote(), timeout=60)

    log_dir = os.path.join(session_dir, "logs")

    def has_postmortem():
        return any(n.startswith("postmortem-")
                   for n in os.listdir(log_dir))

    _wait_for(has_postmortem, timeout=15.0,
              desc="a postmortem file in the worker log dir")
    path = next(os.path.join(log_dir, n) for n in os.listdir(log_dir)
                if n.startswith("postmortem-"))
    data = json.loads(open(path).read())
    assert "synthetic worker crash" in data["reason"]
    assert data["stacks"]
