"""Object spilling tests: objects that overflow the shm store land on
disk and remain readable (reference strategy:
python/ray/tests/test_object_spilling*.py)."""

import numpy as np
import pytest

import ray_tpu


def test_spill_when_store_full():
    # Tiny 8MB store; pinned reads make eviction impossible, so later
    # objects must overflow to disk.
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=8 << 20)
    try:
        refs = []
        arrays = []
        for i in range(6):  # 6 x 3MB > 8MB capacity
            a = np.full(3 << 18, i, dtype=np.float64)  # ~2MB... 3MB-ish
            arrays.append(a)
            refs.append(ray_tpu.put(a))
        # Everything is still readable, including overflowed objects.
        for i, ref in enumerate(refs):
            out = ray_tpu.get(ref, timeout=60)
            assert out[0] == float(i)
            assert out.shape == arrays[i].shape

        # Workers can read spilled objects too.
        @ray_tpu.remote
        def head_of(x):
            return float(x[0])

        vals = ray_tpu.get([head_of.remote(r) for r in refs], timeout=120)
        assert vals == [float(i) for i in range(6)]
    finally:
        ray_tpu.shutdown()


def test_spilled_object_from_worker_return():
    ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=8 << 20)
    try:
        @ray_tpu.remote
        def make(i):
            return np.full(3 << 18, i, dtype=np.float64)

        refs = [make.remote(i) for i in range(6)]
        # Hold all refs (pinned by ownership) and read them all back.
        outs = ray_tpu.get(refs, timeout=120)
        assert [o[0] for o in outs] == [float(i) for i in range(6)]
    finally:
        ray_tpu.shutdown()
