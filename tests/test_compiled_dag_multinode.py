"""Compiled-DAG pipeline across two node agents (separate arenas /
sessions on one machine) — the cross-process pipeline-parallel shape
(reference: test_accelerated_dag.py multi-actor pipelines)."""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode


@ray_tpu.remote
class Adder:
    def __init__(self, add):
        self.add = add

    def fwd(self, x):
        return x + self.add


@pytest.fixture(scope="module")
def two_agent_cluster():
    """Head (hostA) + one node-agent subprocess (hostB) on this machine
    — same shape as test_multihost's fixture, local to this module."""
    import os
    import subprocess
    import sys

    ray_tpu.init(num_cpus=2, num_tpus=0, resources={"hostA": 2})
    from ray_tpu import api

    head_port = api._global_node.port
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--head-host", "127.0.0.1", "--head-port", str(head_port),
         "--num-cpus", "2", "--resources", '{"hostB": 2}',
         "--object-store-memory", str(128 << 20)],
        env=dict(os.environ),
    )
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get("hostB"):
            break
        if agent.poll() is not None:
            raise RuntimeError("node agent exited during startup")
        time.sleep(0.2)
    else:
        raise TimeoutError("node agent never joined")
    yield agent
    agent.terminate()
    agent.wait(timeout=30)
    ray_tpu.shutdown()


def test_compiled_pipeline_across_two_node_agents(two_agent_cluster):
    """Cross-process pipeline parallelism: stage actors pinned to two
    different node agents (separate arenas/sessions), wired by shm
    channels (same physical host — the channels' scope; cross-host
    pipelines ride in-graph ICI collectives instead, see
    parallel/pipeline.py)."""
    s1 = Adder.options(resources={"hostA": 1}).remote(1)
    s2 = Adder.options(resources={"hostB": 1}).remote(10)
    ray_tpu.get([s1.fwd.remote(0), s2.fwd.remote(0)], timeout=120)
    with InputNode() as inp:
        node = s2.fwd.bind(s1.fwd.bind(inp))
    cd = node.experimental_compile()
    try:
        for i in range(20):
            assert cd.execute(i, timeout=120) == i + 11
    finally:
        cd.teardown()
