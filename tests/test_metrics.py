"""Built-in metrics plane: registry semantics, merge/render math, the
push throttle, and an end-to-end instrumented round-trip (reference
strategy: test_metrics_agent.py + test_metrics.py — app metrics flow out
to Prometheus and built-in ray_* metrics cover the runtime)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import metrics as um
from ray_tpu.util import telemetry


# ---------------------------------------------------------------------------
# registry semantics (satellite: idempotent duplicate-name registration)
# ---------------------------------------------------------------------------


def test_duplicate_name_returns_existing_counter():
    c1 = um.Counter("tm_dup_counter", "first", tag_keys=("k",))
    c1.inc(2, {"k": "a"})
    c2 = um.Counter("tm_dup_counter", "second", tag_keys=("k",))
    assert c2 is c1
    c2.inc(3, {"k": "a"})
    assert c1._values[(("k", "a"),)] == 5.0


def test_duplicate_registration_merges_tag_keys():
    g1 = um.Gauge("tm_dup_gauge", tag_keys=("a",))
    g2 = um.Gauge("tm_dup_gauge", tag_keys=("b",))
    assert g2 is g1
    # Both declarations' tags usable after the merge.
    g1.set(1.0, {"a": "x"})
    g1.set(2.0, {"b": "y"})


def test_duplicate_name_type_mismatch_raises():
    um.Counter("tm_dup_mismatch")
    with pytest.raises(TypeError):
        um.Gauge("tm_dup_mismatch")
    with pytest.raises(TypeError):
        um.Histogram("tm_dup_mismatch")


def test_histogram_reregistration_keeps_buckets():
    h1 = um.Histogram("tm_dup_hist", boundaries=[0.1, 1.0])
    h1.observe(0.5)
    h2 = um.Histogram("tm_dup_hist")
    assert h2 is h1
    assert h1.boundaries == [0.1, 1.0]
    with pytest.raises(TypeError):
        um.Histogram("tm_dup_hist", boundaries=[0.2, 2.0])


def test_undeclared_tag_key_rejected():
    c = um.Counter("tm_tagcheck", tag_keys=("k",))
    with pytest.raises(ValueError):
        c.inc(1, {"nope": "x"})


# ---------------------------------------------------------------------------
# histogram bucket math + rendering
# ---------------------------------------------------------------------------


def test_histogram_bucket_math():
    h = um.Histogram("tm_hist_math", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = h._snapshot()
    [(tags, counts)] = snap["hists"]
    assert tags == []
    # [<=0.1, <=1.0, +inf, sum, count]
    assert counts == [1, 1, 1, 5.55, 3]


def test_render_prometheus_golden():
    merged = {
        "tm_requests_total": {
            "type": "counter", "description": "reqs",
            "values": {(("m", "get"),): 3.0},
        },
        "tm_lat_seconds": {
            "type": "histogram", "description": "lat",
            "boundaries": [0.1, 1.0],
            "values": {(): [1, 1, 1, 5.55, 3]},
        },
    }
    text = um.render_prometheus(merged)
    assert text == (
        "# HELP tm_lat_seconds lat\n"
        "# TYPE tm_lat_seconds histogram\n"
        'tm_lat_seconds_bucket{le="0.1"} 1\n'
        'tm_lat_seconds_bucket{le="1.0"} 2\n'
        'tm_lat_seconds_bucket{le="+Inf"} 3\n'
        "tm_lat_seconds_sum 5.55\n"
        "tm_lat_seconds_count 3\n"
        "# HELP tm_requests_total reqs\n"
        "# TYPE tm_requests_total counter\n"
        'tm_requests_total{m="get"} 3.0\n'
    )


# ---------------------------------------------------------------------------
# push throttle (satellite: cw-less call must not consume the window)
# ---------------------------------------------------------------------------


def test_maybe_push_does_not_consume_window_without_worker(monkeypatch):
    import ray_tpu.core.object_ref as object_ref_mod

    monkeypatch.setattr(object_ref_mod, "get_core_worker", lambda: None)
    saved = um._last_push
    um._last_push = 0.0
    try:
        um._maybe_push()
        assert um._last_push == 0.0, (
            "throttle window consumed before a push was possible")
    finally:
        um._last_push = saved


def test_maybe_push_delivers_once_worker_exists(monkeypatch):
    import ray_tpu.core.object_ref as object_ref_mod

    pushed = []

    class _WID:
        @staticmethod
        def hex():
            return "f" * 32

    class _Head:
        @staticmethod
        def call(method, payload):
            async def _noop():
                return {}

            pushed.append((method, payload))
            return _noop()

    class _Loop:
        @staticmethod
        def submit(coro):
            coro.close()

    class _CW:
        worker_id = _WID()
        head = _Head()
        loop_thread = _Loop()

    monkeypatch.setattr(object_ref_mod, "get_core_worker", lambda: _CW())
    saved = um._last_push
    um._last_push = 0.0
    try:
        um.Counter("tm_push_probe").inc()
        assert um._last_push > 0.0
        assert any(p[0] == "kv_put" and p[1]["ns"] == "metrics"
                   for p in pushed)
    finally:
        um._last_push = saved


# ---------------------------------------------------------------------------
# timeline export (satellite: still-RUNNING tasks stay visible)
# ---------------------------------------------------------------------------


def _timeline_mod():
    # ray_tpu.util re-exports the timeline FUNCTION under the module's
    # name; go through sys.modules for the module itself.
    import importlib

    return importlib.import_module("ray_tpu.util.timeline")


def test_timeline_emits_open_begin_events():
    tl = _timeline_mod()
    events = [
        {"task_id": "t1", "state": "RUNNING", "ts": 1.0, "name": "f",
         "worker_id": "w1", "type": "NORMAL_TASK"},
        {"task_id": "t1", "state": "FINISHED", "ts": 2.0, "name": "f",
         "worker_id": "w1", "type": "NORMAL_TASK"},
        {"task_id": "t2", "state": "RUNNING", "ts": 1.5, "name": "hung",
         "worker_id": "w2", "type": "NORMAL_TASK"},
    ]
    # Task-lane semantics only: exclude the telemetry and
    # flight-recorder lanes that otherwise merge into the export.
    trace = tl.timeline(events=events, include_telemetry=False,
                        include_flight=False)
    by_ph = {ev["ph"]: ev for ev in trace}
    assert set(by_ph) == {"X", "B"}
    assert by_ph["X"]["name"] == "f"
    assert by_ph["X"]["dur"] == pytest.approx(1e6)
    assert by_ph["B"]["name"] == "hung"  # visible, not dropped
    assert by_ph["B"]["args"]["state"] == "RUNNING"


def test_timeline_telemetry_lanes():
    tl = _timeline_mod()
    evs = [
        {"cat": "objects", "name": "pull abc", "ts": 1.0, "dur": 0.5,
         "args": {"status": "ok"}},
        {"cat": "retry", "name": "retry push_tasks", "ts": 2.0},
    ]
    trace = tl.telemetry_trace_events(evs)
    assert trace[0]["ph"] == "X" and trace[0]["tid"] == "objects"
    assert trace[0]["dur"] == pytest.approx(0.5e6)
    assert trace[1]["ph"] == "i" and trace[1]["tid"] == "retry"


# ---------------------------------------------------------------------------
# end-to-end: instrumented round-trip + cross-process merge
# ---------------------------------------------------------------------------


def _wait_for_metrics(predicate, timeout=45.0):
    deadline = time.time() + timeout
    merged = {}
    while time.time() < deadline:
        um.flush_metrics()
        merged = um.collect_metrics()
        if predicate(merged):
            return merged
        time.sleep(0.3)
    raise AssertionError(
        f"metrics never satisfied predicate; have {sorted(merged)}")


def _counter_total(merged, name):
    return sum(merged[name]["values"].values()) if name in merged else 0.0


def test_roundtrip_increments_core_metrics(ray_start):
    @ray_tpu.remote
    def flush_and_echo(x):
        # Flush from inside the task so the worker's RUNNING counter is
        # in the KV before the driver collects.
        from ray_tpu.util import metrics as wm

        wm.flush_metrics()
        return x + 1

    assert ray_tpu.get(flush_and_echo.remote(1), timeout=120) == 2

    need = ["ray_tpu_rpc_client_latency_seconds",
            "ray_tpu_rpc_sent_bytes_total",
            "ray_tpu_rpc_recv_bytes_total",
            "ray_tpu_tasks_total",
            "ray_tpu_scheduler_leases_granted_total",
            "ray_tpu_scheduler_placement_latency_seconds"]

    def _tasks_by_state(m):
        return {dict(tk).get("state"): v
                for tk, v in m["ray_tpu_tasks_total"]["values"].items()}

    # The RUNNING count arrives with the WORKER's async push — wait for
    # it too, not just for the metric names (driver-only snapshot).
    merged = _wait_for_metrics(
        lambda m: all(n in m for n in need)
        and _tasks_by_state(m).get("RUNNING", 0) >= 1)
    tasks = _tasks_by_state(merged)
    assert tasks.get("SUBMITTED", 0) >= 1  # driver side
    assert tasks.get("RUNNING", 0) >= 1    # worker side (merged)
    assert _counter_total(merged, "ray_tpu_rpc_sent_bytes_total") > 0
    # The histogram merged across processes keeps count/sum coherent.
    hist = merged["ray_tpu_rpc_client_latency_seconds"]
    for counts in hist["values"].values():
        assert counts[-1] >= 1
    # Prometheus rendering of the merged view is non-empty and typed.
    text = um.prometheus_text()
    assert "# TYPE ray_tpu_tasks_total counter" in text
    assert "ray_tpu_rpc_client_latency_seconds_bucket" in text


def test_fault_injected_partition_shows_in_retry_counters(ray_start):
    from ray_tpu.core import rpc as rpc_mod

    before_retries = 0.0
    um.flush_metrics()
    try:
        merged = um.collect_metrics()
        before_retries = _counter_total(merged, "ray_tpu_retries_total")
    except Exception:
        pass

    fi = rpc_mod.get_fault_injector()
    fi.install("partition", method="push_tasks", direction="send",
               max_matches=1)
    try:
        @ray_tpu.remote
        def g():
            return 42

        assert ray_tpu.get(g.remote(), timeout=120) == 42
    finally:
        fi.reset()
        rpc_mod.reset_fault_injector()

    merged = _wait_for_metrics(
        lambda m: ("ray_tpu_rpc_faults_injected_total" in m
                   and _counter_total(m, "ray_tpu_retries_total")
                   > before_retries))
    faults = {dict(tk).get("action"): v for tk, v in
              merged["ray_tpu_rpc_faults_injected_total"]["values"].items()}
    assert faults.get("partition", 0) >= 1
    sites = {dict(tk).get("site") for tk in
             merged["ray_tpu_retries_total"]["values"]}
    assert "push_tasks" in sites


def test_histogram_cross_process_merge(ray_start):
    name = "tm_merge_hist"
    h = um.Histogram(name, boundaries=[0.1, 1.0])
    h.observe(0.05)

    @ray_tpu.remote
    def observe_remote():
        from ray_tpu.util import metrics as wm

        wh = wm.Histogram("tm_merge_hist", boundaries=[0.1, 1.0])
        wh.observe(0.5)
        wm.flush_metrics()
        return True

    assert ray_tpu.get(observe_remote.remote(), timeout=120)
    merged = _wait_for_metrics(
        lambda m: name in m
        and next(iter(m[name]["values"].values()))[-1] >= 2)
    [(tags, counts)] = list(merged[name]["values"].items())
    assert counts[-1] >= 2          # merged count
    assert counts[0] >= 1           # <=0.1 bucket (driver)
    assert counts[1] >= 1           # <=1.0 bucket (worker)


def test_counter_cross_process_merge(ray_start):
    c = um.Counter("tm_merge_counter", tag_keys=("who",))
    c.inc(1, {"who": "driver"})

    @ray_tpu.remote
    def inc_remote():
        from ray_tpu.util import metrics as wm

        wc = wm.Counter("tm_merge_counter", tag_keys=("who",))
        wc.inc(2, {"who": "worker"})
        wm.flush_metrics()
        return True

    assert ray_tpu.get(inc_remote.remote(), timeout=120)
    merged = _wait_for_metrics(
        lambda m: "tm_merge_counter" in m
        and len(m["tm_merge_counter"]["values"]) >= 2)
    vals = {dict(tk)["who"]: v
            for tk, v in merged["tm_merge_counter"]["values"].items()}
    assert vals.get("driver") == 1.0
    assert vals.get("worker") == 2.0


def test_timeline_export_from_live_cluster(ray_start):
    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get(noop.remote(), timeout=120)
    time.sleep(1.5)  # task-event buffer flush interval
    tl = _timeline_mod()
    trace = tl.timeline()
    assert trace, "timeline empty after running tasks"
    assert any(ev["ph"] in ("X", "B") for ev in trace)
