"""Tests for the native C++ shared-memory arena (cpp/tpustore) and its
integration as the node object store (reference test analog:
src/ray/object_manager/plasma store tests + python object store tests)."""

import os

import numpy as np
import pytest

from ray_tpu.core.native_store import NativeArena, get_library

pytestmark = pytest.mark.skipif(
    get_library() is None, reason="native store build unavailable")


@pytest.fixture
def arena():
    name = f"rtpu_test_{os.getpid()}"
    a = NativeArena.create(name, 1 << 20)
    assert a is not None
    yield a
    a.destroy()


def test_create_seal_lookup(arena):
    key = bytes(range(20))
    data = b"hello arena" * 10
    assert arena.create_and_seal(key, data)
    view = arena.lookup(key)
    assert bytes(view[:len(data)]) == data
    assert arena.contains(key)
    assert arena.num_objects() == 1
    assert arena.used_bytes() >= len(data)


def test_idempotent_create(arena):
    key = b"k" * 20
    assert arena.create_and_seal(key, b"v1")
    assert not arena.create_and_seal(key, b"v2")  # already exists
    assert bytes(arena.lookup(key)[:2]) == b"v1"


def test_delete_frees_space(arena):
    key = b"d" * 20
    arena.create_and_seal(key, os.urandom(10000))
    used = arena.used_bytes()
    arena.delete(key)
    assert arena.lookup(key) is None
    assert arena.used_bytes() < used
    # Space is reusable.
    key2 = b"e" * 20
    arena.create_and_seal(key2, os.urandom(10000))


def test_lru_eviction_and_pinning(arena):
    pinned = b"p" * 20
    arena.create_and_seal(pinned, b"precious")
    for i in range(60):
        arena.create_and_seal(i.to_bytes(20, "little"), os.urandom(40000),
                              pin_primary=False)
    assert arena.num_evicted() > 0
    assert arena.contains(pinned)  # pinned survived the pressure
    arena.unpin(pinned)


def test_lookup_bumps_lru(arena):
    hot = b"h" * 20
    arena.create_and_seal(hot, os.urandom(1000), pin_primary=False)
    cold = b"c" * 20
    arena.create_and_seal(cold, os.urandom(1000), pin_primary=False)
    # Touch hot repeatedly while filling; cold should evict first.
    for i in range(50):
        arena.lookup(hot, pin_for_read=False)
        arena.create_and_seal(i.to_bytes(20, "big"), os.urandom(30000),
                              pin_primary=False)
    if arena.num_evicted() > 0 and arena.contains(hot):
        assert not arena.contains(cold) or arena.contains(hot)


def test_too_large_object_rejected(arena):
    from ray_tpu.exceptions import ObjectStoreFullError

    with pytest.raises(ObjectStoreFullError):
        arena.create_and_seal(b"x" * 20, os.urandom(2 << 20))


def _attach_child(name, q):
    a = NativeArena.attach(name)
    v = a.lookup(b"z" * 20)
    q.put(bytes(v[:11]))
    a.create_and_seal(b"y" * 20, b"from-child")


def test_cross_process_attach(arena):
    """A spawned process attaches and reads/writes the same arena."""
    import multiprocessing as mp

    key = b"z" * 20
    arena.create_and_seal(key, b"from-parent")
    child = _attach_child
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=child, args=(arena.name, q))
    p.start()
    got = q.get(timeout=60)
    p.join(timeout=60)
    assert got == b"from-parent"
    assert bytes(arena.lookup(b"y" * 20)[:10]) == b"from-child"


def test_framework_uses_arena():
    """End-to-end: large objects round-trip through the arena across
    worker processes, zero-copy on the read side."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        from ray_tpu import api as _api

        assert _api._global_node.arena is not None, \
            "native arena not active"

        big = np.arange(500_000, dtype=np.float64)  # 4MB > inline cutoff
        ref = ray_tpu.put(big)

        @ray_tpu.remote
        def consume(x):
            return float(x.sum())

        assert ray_tpu.get(consume.remote(ref), timeout=120) == \
            float(big.sum())

        @ray_tpu.remote
        def produce():
            return np.ones(300_000)  # large return -> arena

        out = ray_tpu.get(produce.remote(), timeout=120)
        assert out.shape == (300_000,)
        assert _api._global_node.arena.num_objects() >= 1
    finally:
        ray_tpu.shutdown()
