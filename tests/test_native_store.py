"""Tests for the native C++ shared-memory arena (cpp/tpustore) and its
integration as the node object store (reference test analog:
src/ray/object_manager/plasma store tests + python object store tests)."""

import os

import numpy as np
import pytest

from ray_tpu.core.native_store import NativeArena, get_library

pytestmark = pytest.mark.skipif(
    get_library() is None, reason="native store build unavailable")


@pytest.fixture
def arena():
    name = f"rtpu_test_{os.getpid()}"
    a = NativeArena.create(name, 1 << 20)
    assert a is not None
    yield a
    a.destroy()


def test_create_seal_lookup(arena):
    key = bytes(range(20))
    data = b"hello arena" * 10
    assert arena.create_and_seal(key, data)
    view = arena.lookup(key)
    assert bytes(view[:len(data)]) == data
    assert arena.contains(key)
    assert arena.num_objects() == 1
    assert arena.used_bytes() >= len(data)


def test_idempotent_create(arena):
    key = b"k" * 20
    assert arena.create_and_seal(key, b"v1")
    assert not arena.create_and_seal(key, b"v2")  # already exists
    assert bytes(arena.lookup(key)[:2]) == b"v1"


def test_delete_frees_space(arena):
    key = b"d" * 20
    arena.create_and_seal(key, os.urandom(10000))
    used = arena.used_bytes()
    arena.delete(key)
    assert arena.lookup(key) is None
    assert arena.used_bytes() < used
    # Space is reusable.
    key2 = b"e" * 20
    arena.create_and_seal(key2, os.urandom(10000))


def test_lru_eviction_and_pinning(arena):
    pinned = b"p" * 20
    arena.create_and_seal(pinned, b"precious")
    for i in range(60):
        arena.create_and_seal(i.to_bytes(20, "little"), os.urandom(40000),
                              pin_primary=False)
    assert arena.num_evicted() > 0
    assert arena.contains(pinned)  # pinned survived the pressure
    arena.unpin(pinned)


def test_lookup_bumps_lru(arena):
    hot = b"h" * 20
    arena.create_and_seal(hot, os.urandom(1000), pin_primary=False)
    cold = b"c" * 20
    arena.create_and_seal(cold, os.urandom(1000), pin_primary=False)
    # Touch hot repeatedly while filling; cold should evict first.
    for i in range(50):
        arena.lookup(hot, pin_for_read=False)
        arena.create_and_seal(i.to_bytes(20, "big"), os.urandom(30000),
                              pin_primary=False)
    if arena.num_evicted() > 0 and arena.contains(hot):
        assert not arena.contains(cold) or arena.contains(hot)


def test_delete_defers_free_under_live_view(arena):
    """Owner delete of a read-pinned object must not free memory under
    the reader's zero-copy view (plasma never reclaims buffers clients
    hold, object_lifecycle_manager.h:101)."""
    import gc

    key = b"v" * 20
    payload = os.urandom(50000)
    arena.create_and_seal(key, payload)
    view = arena.lookup(key)  # takes a read pin
    used_before = arena.used_bytes()
    arena.delete(key)
    # Invisible to lookups, but memory retained while the view lives.
    assert arena.lookup(key) is None
    assert not arena.contains(key)
    assert arena.used_bytes() == used_before
    # Churn the allocator hard: if the extent had been freed, these
    # writes would scribble over the view.
    for i in range(40):
        arena.create_and_seal(i.to_bytes(20, "little"), os.urandom(20000),
                              pin_primary=False)
    assert bytes(view[:len(payload)]) == payload
    # Releasing the last view frees the zombie.
    used_with_zombie = arena.used_bytes()
    del view
    gc.collect()
    assert arena.used_bytes() <= used_with_zombie - len(payload)


def test_concurrent_delete_while_reading(arena):
    """Readers repeatedly materialize views while a deleter frees the
    same keys; every materialized view must stay byte-stable."""
    import threading

    keys = [bytes([i]) * 20 for i in range(8)]
    payloads = {k: bytes([k[0]]) * 30000 for k in keys}
    errors = []

    def reader():
        for _ in range(30):
            for k in keys:
                v = arena.lookup(k)
                if v is None:
                    continue
                b = bytes(v[:100])
                if b != payloads[k][:100]:
                    errors.append((k, b[:8]))

    def churn():
        for r in range(30):
            for k in keys:
                arena.delete(k)
                arena.create_and_seal(k, payloads[k], pin_primary=False)

    for k in keys:
        arena.create_and_seal(k, payloads[k], pin_primary=False)
    threads = [threading.Thread(target=reader) for _ in range(3)]
    threads.append(threading.Thread(target=churn))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[:3]


def test_seal_after_delete_mid_write(arena):
    """Delete landing between alloc and seal: the writer's seal reports
    failure and the entry is freed once the write hold drops."""
    import ctypes

    lib = arena._lib
    key = b"w" * 20
    off = ctypes.c_uint64()
    idx = lib.ts_alloc(arena._h, key, 1000, ctypes.byref(off))
    assert idx >= 0
    used_mid = arena.used_bytes()
    arena.delete(key)  # write hold pins it -> zombie, memory retained
    assert arena.used_bytes() == used_mid
    rc = lib.ts_seal_idx(arena._h, idx, key, 1)
    assert rc == -5  # TS_ESTATE: deleted under the writer
    assert arena.used_bytes() < used_mid  # freed with the write hold
    assert not arena.contains(key)


def test_reput_while_zombie_held(arena):
    """Re-creating a key whose old zombie is still read-pinned must
    succeed: the new live entry coexists with the zombie."""
    import gc

    key = b"r" * 20
    arena.create_and_seal(key, b"old-value", pin_primary=False)
    view = arena.lookup(key)
    arena.delete(key)  # zombie while `view` lives
    assert arena.create_and_seal(key, b"new-value", pin_primary=False)
    assert bytes(arena.lookup(key)[:9]) == b"new-value"
    assert bytes(view[:9]) == b"old-value"  # old view untouched
    del view
    gc.collect()
    assert bytes(arena.lookup(key)[:9]) == b"new-value"


def test_dead_reader_pins_are_reaped(arena):
    """Read pins leaked by a crashed process must not wedge the arena:
    allocation pressure reaps them (plasma disconnect-cleanup analog)."""
    import multiprocessing as mp

    key = b"s" * 20
    arena.create_and_seal(key, os.urandom(600_000), pin_primary=False)

    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_crash_holding_pin, args=(arena.name, key))
    p.start()
    p.join(timeout=60)
    # The 600KB object is read-pinned by a dead pid; allocating another
    # 600KB in the 1MB arena only fits if the reap releases that pin
    # and the LRU eviction can then claim the object.
    key2 = b"t" * 20
    assert arena.create_and_seal(key2, os.urandom(600_000),
                                 pin_primary=False)
    assert arena.contains(key2)


def test_pin_unpin_rc(arena):
    missing = b"n" * 20
    assert not arena.pin(missing)
    assert not arena.unpin(missing)
    key = b"q" * 20
    arena.create_and_seal(key, b"data", pin_primary=False)
    assert arena.pin(key)
    assert arena.unpin(key)


def test_too_large_object_rejected(arena):
    from ray_tpu.exceptions import ObjectStoreFullError

    with pytest.raises(ObjectStoreFullError):
        arena.create_and_seal(b"x" * 20, os.urandom(2 << 20))


def _crash_holding_pin(name, key):
    a = NativeArena.attach(name)
    v = a.lookup(key)  # read pin, attributed to this pid
    assert v is not None
    os._exit(1)  # no finalizers run


def _attach_child(name, q):
    a = NativeArena.attach(name)
    v = a.lookup(b"z" * 20)
    q.put(bytes(v[:11]))
    a.create_and_seal(b"y" * 20, b"from-child")


def test_cross_process_attach(arena):
    """A spawned process attaches and reads/writes the same arena."""
    import multiprocessing as mp

    key = b"z" * 20
    arena.create_and_seal(key, b"from-parent")
    child = _attach_child
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=child, args=(arena.name, q))
    p.start()
    got = q.get(timeout=60)
    p.join(timeout=60)
    assert got == b"from-parent"
    assert bytes(arena.lookup(b"y" * 20)[:10]) == b"from-child"


def test_framework_uses_arena():
    """End-to-end: large objects round-trip through the arena across
    worker processes, zero-copy on the read side."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        from ray_tpu import api as _api

        assert _api._global_node.arena is not None, \
            "native arena not active"

        big = np.arange(500_000, dtype=np.float64)  # 4MB > inline cutoff
        ref = ray_tpu.put(big)

        @ray_tpu.remote
        def consume(x):
            return float(x.sum())

        assert ray_tpu.get(consume.remote(ref), timeout=120) == \
            float(big.sum())

        @ray_tpu.remote
        def produce():
            return np.ones(300_000)  # large return -> arena

        out = ray_tpu.get(produce.remote(), timeout=120)
        assert out.shape == (300_000,)
        assert _api._global_node.arena.num_objects() >= 1
    finally:
        ray_tpu.shutdown()
