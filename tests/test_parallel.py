"""Mesh / sharding / sequence-parallel attention tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import flash_attention, reference_attention
from ray_tpu.parallel import MeshConfig, create_mesh, logical_sharding
from ray_tpu.parallel.ring_attention import (
    make_sequence_parallel_attention,
)


def test_mesh_resolution():
    cfg = MeshConfig(data=-1, tensor=2)
    sizes = cfg.resolve(8)
    assert sizes["data"] == 4 and sizes["tensor"] == 2


def test_mesh_invalid():
    with pytest.raises(ValueError):
        MeshConfig(data=3, tensor=2).resolve(8)


def test_create_mesh_shapes():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == 2
    assert mesh.devices.size == 8


def test_logical_sharding_rules():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    s = logical_sharding(mesh, ("embed", "heads"))
    assert s.spec == jax.sharding.PartitionSpec("fsdp", "tensor")
    # Axes absent from a smaller mesh get dropped.
    mesh2 = create_mesh(MeshConfig(data=8, axis_order=("data",)))
    s2 = logical_sharding(mesh2, ("embed", "heads"))
    assert s2.spec == jax.sharding.PartitionSpec(None, None)


def test_flash_attention_matches_reference_cpu():
    key = jax.random.PRNGKey(0)
    B, S, H, D = 2, 256, 2, 64
    q = jax.random.normal(key, (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    ref = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)  # interpret mode on CPU
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-2, atol=2e-3)


def test_flash_attention_blockwise_backward_matches():
    # The custom VJP must match reference gradients without ever
    # building the [B, H, S, S] score tensor.
    rng = jax.random.PRNGKey(3)
    q, k, v = [jax.random.normal(kk, (2, 256, 4, 64), jnp.float32) * 0.3
               for kk in jax.random.split(rng, 3)]
    for causal in (True, False):
        gf = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(
            reference_attention(*a, causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


def test_llama_remat_policy_validation():
    from ray_tpu.models.llama import LlamaConfig

    with pytest.raises(ValueError, match="remat_policy"):
        LlamaConfig.tiny(remat_policy="dot")
    LlamaConfig.tiny(remat_policy="dots")  # valid


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_sequence_parallel_attention(kind):
    mesh = create_mesh(MeshConfig(data=2, sequence=4))
    B, S, H, D = 2, 256, 4, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    sp_attn = make_sequence_parallel_attention(mesh, kind=kind, causal=True)
    out = jax.jit(sp_attn)(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-2, atol=2e-3)


def test_ring_attention_non_causal():
    mesh = create_mesh(MeshConfig(data=1, sequence=8))
    B, S, H, D = 1, 512, 2, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    sp_attn = make_sequence_parallel_attention(mesh, kind="ring",
                                               causal=False)
    out = jax.jit(sp_attn)(q, k, v)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-2, atol=2e-3)


def test_ring_attention_grads_flow():
    mesh = create_mesh(MeshConfig(data=2, sequence=4))
    B, S, H, D = 2, 128, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    sp_attn = make_sequence_parallel_attention(mesh, kind="ring")

    def loss(q, k, v):
        return jnp.sum(sp_attn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g = jax.jit(jax.grad(loss))(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=5e-2, atol=5e-3)


def test_flash_pallas_backward_matches_reference():
    """r5: the blocked Pallas backward (dq/dkv kernels driven by the
    forward's saved LSE) must match the XLA reference VJP for both
    causal and full attention (interpret mode on CPU)."""
    import jax

    key = jax.random.PRNGKey(7)
    B, S, H, D = 2, 256, 2, 64
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (B, S, H, D), jnp.float32)
               for i in range(3))
    for causal in (True, False):
        def loss(fn, q, k, v, causal=causal):
            w = jnp.cos(jnp.arange(D))
            return jnp.sum(fn(q, k, v, causal) * w)

        gf = jax.grad(lambda *a: loss(flash_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: loss(reference_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            scale = float(jnp.max(jnp.abs(b))) + 1e-9
            assert float(jnp.max(jnp.abs(a - b))) / scale < 6e-3
