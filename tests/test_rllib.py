"""Tests for ray_tpu.rllib (reference strategy: rllib/tests/ e2e learning
tests + rllib/algorithms/tests unit tests; math parity tests mirror
vtrace_test.py and GAE postprocessing tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import rllib as rl


@pytest.fixture(scope="module")
def rl_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


# -- envs (no cluster) ------------------------------------------------------


def test_cartpole_env():
    env = rl.CartPoleVecEnv(num_envs=4, seed=0)
    obs = env.reset()
    assert obs.shape == (4, 4)
    for _ in range(10):
        obs, rew, term, trunc = env.step(np.random.randint(0, 2, size=4))
        assert obs.shape == (4, 4)
        assert rew.shape == (4,)
    # Always-left policy must eventually terminate some env.
    env.reset(seed=1)
    terms = 0
    for _ in range(200):
        _, _, term, _ = env.step(np.zeros(4, np.int64))
        terms += int(term.sum())
    assert terms > 0


def test_gae_matches_numpy_reference():
    rng = np.random.default_rng(0)
    T, B = 12, 3
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = rng.random((T, B)) < 0.1
    last_values = rng.normal(size=B).astype(np.float32)
    gamma, lam = 0.99, 0.95
    adv, targets = rl.compute_gae(rewards, values, dones, last_values,
                                  gamma=gamma, lam=lam)
    # slow numpy reference
    expect = np.zeros((T, B), np.float32)
    next_adv = np.zeros(B, np.float32)
    next_v = last_values
    for t in reversed(range(T)):
        nt = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_v * nt - values[t]
        next_adv = delta + gamma * lam * nt * next_adv
        expect[t] = next_adv
        next_v = values[t]
    np.testing.assert_allclose(np.asarray(adv), expect, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(targets), expect + values,
                               rtol=1e-4, atol=1e-4)


def test_vtrace_on_policy_reduces_to_gae_targets():
    # With pi == mu (rhos == 1) and no clipping effect, vs should equal
    # the lambda=1 GAE targets (n-step TD).
    rng = np.random.default_rng(1)
    T, B = 10, 2
    logp = rng.normal(size=(T, B)).astype(np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = np.zeros((T, B), bool)
    last_values = rng.normal(size=B).astype(np.float32)
    vs, pg_adv = rl.vtrace(logp, logp, rewards, values, dones, last_values,
                           gamma=0.99)
    adv, targets = rl.compute_gae(rewards, values, dones, last_values,
                                  gamma=0.99, lam=1.0)
    np.testing.assert_allclose(np.asarray(vs), np.asarray(targets),
                               rtol=1e-3, atol=1e-3)


def test_learner_update_decreases_loss():
    spec = rl.RLModuleSpec(rl.Space.box((4,)), rl.Space.discrete(2))
    learner = rl.JaxLearner(spec, rl.ppo_loss, lr=1e-2, seed=0)
    rng = np.random.default_rng(0)
    n = 256
    batch = {
        "obs": rng.normal(size=(n, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=n).astype(np.int32),
        "logp": np.full(n, -0.693, np.float32),
        "advantages": rng.normal(size=n).astype(np.float32),
        "targets": rng.normal(size=n).astype(np.float32),
    }
    first = learner.update(batch)
    for _ in range(20):
        last = learner.update(batch)
    assert last["vf_loss"] < first["vf_loss"]
    assert learner.weights_version == 21


def test_learner_state_roundtrip():
    spec = rl.RLModuleSpec(rl.Space.box((4,)), rl.Space.discrete(2))
    l1 = rl.JaxLearner(spec, rl.ppo_loss, seed=0)
    state = l1.get_state()
    l2 = rl.JaxLearner(spec, rl.ppo_loss, seed=99)
    l2.set_state(state)
    import jax

    t1 = jax.tree.leaves(l1.params)
    t2 = jax.tree.leaves(l2.params)
    for a, b in zip(t1, t2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- end-to-end learning ----------------------------------------------------


def test_ppo_learns_gridworld(rl_cluster):
    config = (rl.PPOConfig()
              .environment("GridWorld-v0", num_envs_per_env_runner=8)
              .env_runners(num_env_runners=2, rollout_fragment_length=32,
                           num_cpus_per_env_runner=0.5)
              .training(lr=5e-3, num_epochs=4, minibatch_size=128,
                        entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    try:
        best = -np.inf
        for i in range(15):
            result = algo.step()
            if "episode_return_mean" in result:
                best = max(best, result["episode_return_mean"])
            if best > 0.8:
                break
        # Optimal GridWorld return is 1 - 0.01*3 ≈ 0.96; random is ~-0.1.
        assert best > 0.5, f"PPO failed to learn: best={best}"
        assert result["timesteps_total"] > 0
    finally:
        algo.cleanup()


def test_ppo_pixel_obs_conv(rl_cluster):
    """Pixel observations route through the conv encoder and train
    end-to-end (PixelGridWorld: (16,16,3) uint8 images)."""
    config = (rl.PPOConfig()
              .environment("PixelGridWorld-v0", num_envs_per_env_runner=4)
              .env_runners(num_env_runners=1, rollout_fragment_length=16,
                           num_cpus_per_env_runner=0.5)
              .training(lr=1e-3, num_epochs=2, minibatch_size=32)
              .debugging(seed=0))
    algo = config.build()
    try:
        from ray_tpu.rllib.rl_module import ActorCriticConv, RLModuleSpec
        from ray_tpu.rllib.env import make_vec

        probe = make_vec("PixelGridWorld-v0", num_envs=1)
        spec = RLModuleSpec(observation_space=probe.observation_space,
                            action_space=probe.action_space)
        assert type(spec.build().net) is ActorCriticConv
        result = algo.step()
        assert result["num_env_steps_sampled_this_iter"] > 0
        assert np.isfinite(result["learner/loss"])
        result = algo.step()  # second step: weights updated + resampled
        assert result["timesteps_total"] > 0
    finally:
        algo.cleanup()


def test_ppo_checkpoint_restore(rl_cluster, tmp_path):
    config = (rl.PPOConfig()
              .environment("GridWorld-v0", num_envs_per_env_runner=4)
              .env_runners(num_env_runners=1, rollout_fragment_length=16,
                           num_cpus_per_env_runner=0.5)
              .debugging(seed=0))
    algo = config.build()
    try:
        algo.step()
        path = algo.save(str(tmp_path / "ck"))
        it = algo.iteration
        algo2 = config.build()
        try:
            algo2.restore(path)
            assert algo2.iteration == it
            import jax

            for a, b in zip(jax.tree.leaves(algo.learner.params),
                            jax.tree.leaves(algo2.learner.params)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        finally:
            algo2.cleanup()
    finally:
        algo.cleanup()


def test_impala_learns_gridworld(rl_cluster):
    config = (rl.IMPALAConfig()
              .environment("GridWorld-v0", num_envs_per_env_runner=8)
              .env_runners(num_env_runners=2, rollout_fragment_length=32,
                           num_cpus_per_env_runner=0.5)
              .training(lr=5e-3, num_batches_per_step=4,
                        entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    try:
        best = -np.inf
        for i in range(20):
            result = algo.step()
            if "episode_return_mean" in result:
                best = max(best, result["episode_return_mean"])
            if best > 0.8:
                break
        assert best > 0.4, f"IMPALA failed to learn: best={best}"
    finally:
        algo.cleanup()


def test_algorithm_with_tune(rl_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    base = (rl.PPOConfig()
            .environment("GridWorld-v0", num_envs_per_env_runner=4)
            .env_runners(num_env_runners=1, rollout_fragment_length=16,
                         num_cpus_per_env_runner=0.4)
            .debugging(seed=0))
    cfgs = []
    for lr in (1e-2, 1e-3):
        c = base.copy().training(lr=lr)
        cfgs.append({"algo_config": c})
    tuner = tune.Tuner(
        rl.PPO,
        param_space={"algo_config": tune.grid_search(
            [c["algo_config"] for c in cfgs])},
        tune_config=tune.TuneConfig(metric="learner/loss", mode="min",
                                    max_concurrent_trials=1),
        run_config=RunConfig(name="rl_tune", storage_path=str(tmp_path),
                             stop={"training_iteration": 2}),
        resources_per_trial={"num_cpus": 1},
    )
    grid = tuner.fit()
    assert len(grid) == 2
    assert not grid.errors


def test_atari_like_env_contract():
    """r5: the Atari-class env (84x84x4 uint8 frame stacks) honors the
    VectorEnv contract and feeds the conv-tower sampling path."""
    import numpy as np

    from ray_tpu.rllib.env import make_vec
    from ray_tpu.rllib.env_runner import EnvRunner
    from ray_tpu.rllib.rl_module import RLModuleSpec

    env = make_vec("AtariLike-v0", num_envs=4, seed=1)
    obs = env.reset()
    assert obs.shape == (4, 84, 84, 4) and obs.dtype == np.uint8
    rng = np.random.default_rng(0)
    for _ in range(30):
        obs, rew, term, trunc = env.step(
            rng.integers(0, 6, 4).astype(np.int32))
    assert obs[..., -1].max() == 255  # something rendered
    probe = make_vec("AtariLike-v0", num_envs=1)
    spec = RLModuleSpec(observation_space=probe.observation_space,
                        action_space=probe.action_space)
    runner = EnvRunner("AtariLike-v0", num_envs=4, rollout_length=8,
                       module_spec=spec, seed=0)
    batch = runner.sample()
    assert batch["obs"].shape == (8, 4, 84, 84, 4)
    assert batch["obs"].dtype == np.uint8  # raw bytes in rollouts


def test_algorithm_evaluate(rl_cluster):
    algo = (rl.PPOConfig()
            .environment("CartPole-v1", num_envs_per_env_runner=4)
            .env_runners(num_env_runners=2, rollout_fragment_length=16,
                         num_cpus_per_env_runner=0.5)
            .training(train_batch_size=128, minibatch_size=64,
                      num_epochs=1)
            .evaluation(evaluation_interval=2,
                        evaluation_num_episodes=6)
            .debugging(seed=0)
            .build())
    try:
        # Explicit evaluate(): greedy rollouts on fresh envs.
        ev = algo.evaluate(6)
        assert ev["episodes"] >= 6
        assert ev["episode_return_mean"] > 0
        assert ev["episode_len_mean"] > 0
        # Interval-driven: iteration 2 carries an evaluation block.
        r1 = algo.step()
        assert "evaluation" not in r1
        r2 = algo.step()
        assert r2["evaluation"]["episodes"] >= 6
    finally:
        algo.stop()
