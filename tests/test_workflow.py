"""Tests for ray_tpu.workflow + ray_tpu.dag (reference strategy:
python/ray/workflow/tests/test_basic_workflows.py, test_recovery.py)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(scope="module")
def wf_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def mul(a, b):
    return a * b


def test_dag_bind_execute(wf_cluster):
    dag = add.bind(mul.bind(2, 3), add.bind(1, 1))
    ref = dag.execute()
    assert ray_tpu.get(ref, timeout=60) == 8


def test_workflow_run(wf_cluster, tmp_path):
    dag = mul.bind(add.bind(2, 3), 10)
    out = workflow.run(dag, workflow_id="wf1",
                       storage_dir=str(tmp_path))
    assert out == 50
    assert workflow.get_status("wf1", storage_dir=str(tmp_path)) == \
        "SUCCESSFUL"
    assert workflow.get_output("wf1", storage_dir=str(tmp_path)) == 50
    assert ("wf1", "SUCCESSFUL") in workflow.list_all(str(tmp_path))
    # Idempotent: re-running returns the recorded output.
    assert workflow.run(dag, workflow_id="wf1",
                        storage_dir=str(tmp_path)) == 50


_marker_path = None


@ray_tpu.remote
def count_calls(x, marker):
    # Append one line per execution so the test can count replays.
    with open(marker, "a") as f:
        f.write("x\n")
    return x + 1


@ray_tpu.remote
def fail_once(x, marker):
    if not os.path.exists(marker + ".attempted"):
        open(marker + ".attempted", "w").close()
        raise RuntimeError("transient failure")
    return x * 100


def test_workflow_resume_skips_completed_steps(wf_cluster, tmp_path):
    marker = str(tmp_path / "calls.txt")
    dag = fail_once.bind(
        count_calls.bind(1, marker), str(tmp_path / "f"))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf_resume",
                     storage_dir=str(tmp_path))
    assert workflow.get_status(
        "wf_resume", storage_dir=str(tmp_path)) == "FAILED"
    # First step ran exactly once and was checkpointed.
    assert open(marker).read().count("x") == 1
    out = workflow.resume("wf_resume", storage_dir=str(tmp_path))
    assert out == 200
    # The completed step was NOT re-executed on resume.
    assert open(marker).read().count("x") == 1
    assert workflow.get_status(
        "wf_resume", storage_dir=str(tmp_path)) == "SUCCESSFUL"


def test_workflow_run_async(wf_cluster, tmp_path):
    dag = add.bind(20, 22)
    wf_id, ref = workflow.run_async(dag, storage_dir=str(tmp_path))
    assert ray_tpu.get(ref, timeout=120) == 42
    assert workflow.get_output(wf_id, storage_dir=str(tmp_path)) == 42


def test_workflow_delete(wf_cluster, tmp_path):
    workflow.run(add.bind(1, 2), workflow_id="wf_del",
                 storage_dir=str(tmp_path))
    workflow.delete("wf_del", storage_dir=str(tmp_path))
    assert workflow.get_status(
        "wf_del", storage_dir=str(tmp_path)) == "NOT_FOUND"


@ray_tpu.remote
def total(xs):
    return sum(xs)


def test_nested_container_args(wf_cluster, tmp_path):
    dag = total.bind([add.bind(1, 2), mul.bind(2, 2), 5])
    assert ray_tpu.get(dag.execute(), timeout=60) == 12
    out = workflow.run(dag, workflow_id="wf_nested",
                       storage_dir=str(tmp_path))
    assert out == 12


def test_input_node(wf_cluster):
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = add.bind(inp, 10)
    assert ray_tpu.get(dag.execute(7), timeout=60) == 17
    with pytest.raises(ValueError, match="without an input"):
        dag.execute()


def test_workflow_id_reuse_different_dag_raises(wf_cluster, tmp_path):
    workflow.run(add.bind(1, 2), workflow_id="wf_reuse",
                 storage_dir=str(tmp_path))
    with pytest.raises(ValueError, match="different DAG"):
        workflow.run(mul.bind(add.bind(1, 1), 3), workflow_id="wf_reuse",
                     storage_dir=str(tmp_path))


def test_readonly_status_does_not_create_dirs(wf_cluster, tmp_path):
    assert workflow.get_status("nope", storage_dir=str(tmp_path)) == \
        "NOT_FOUND"
    assert workflow.list_all(str(tmp_path)) == []


def test_diamond_dag_shared_node_runs_once(wf_cluster, tmp_path):
    marker = str(tmp_path / "shared.txt")
    shared = count_calls.bind(5, marker)
    dag = add.bind(mul.bind(shared, 2), mul.bind(shared, 3))
    out = workflow.run(dag, workflow_id="wf_diamond",
                       storage_dir=str(tmp_path))
    assert out == 6 * 2 + 6 * 3
    assert open(marker).read().count("x") == 1  # shared step ran once
