"""Tests for runtime envs, the multiprocessing Pool shim, and the joblib
backend (reference strategy: python/ray/tests/test_runtime_env*.py,
util/multiprocessing tests, util/joblib tests)."""

import os

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def re_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_task_env_vars(re_cluster):
    @ray_tpu.remote
    def read_env(key):
        return os.environ.get(key)

    val = ray_tpu.get(
        read_env.options(runtime_env={
            "env_vars": {"RTPU_TEST_VAR": "hello"}}).remote("RTPU_TEST_VAR"),
        timeout=60)
    assert val == "hello"
    # Plain task on a (possibly reused) worker must NOT see the var.
    val2 = ray_tpu.get(read_env.remote("RTPU_TEST_VAR"), timeout=60)
    assert val2 is None


def test_task_working_dir(re_cluster, tmp_path):
    (tmp_path / "data.txt").write_text("payload")

    @ray_tpu.remote
    def read_local():
        with open("data.txt") as f:
            return f.read()

    out = ray_tpu.get(
        read_local.options(runtime_env={
            "working_dir": str(tmp_path)}).remote(), timeout=60)
    assert out == "payload"


def test_actor_keeps_env(re_cluster):
    class EnvActor:
        def read(self, key):
            return os.environ.get(key)

    a = (ray_tpu.remote(EnvActor)
         .options(num_cpus=0.5,
                  runtime_env={"env_vars": {"RTPU_ACTOR_VAR": "sticky"}})
         .remote())
    assert ray_tpu.get(a.read.remote("RTPU_ACTOR_VAR"), timeout=60) == \
        "sticky"
    # Still set on the second call (actors keep their env for life).
    assert ray_tpu.get(a.read.remote("RTPU_ACTOR_VAR"), timeout=60) == \
        "sticky"
    ray_tpu.kill(a)


def test_unsupported_runtime_env_key_errors(re_cluster):
    @ray_tpu.remote
    def noop():
        return 1

    from ray_tpu.exceptions import RayTpuError, TaskError

    with pytest.raises((RayTpuError, TaskError)):
        ray_tpu.get(noop.options(runtime_env={
            "pip": ["requests"]}).remote(), timeout=60)


def _square(x):
    return x * x


def test_multiprocessing_pool(re_cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(_square, range(10)) == [i * i for i in range(10)]
        assert pool.apply(_square, (7,)) == 49
        r = pool.apply_async(_square, (9,))
        assert r.get(timeout=60) == 81
        assert sorted(pool.imap_unordered(_square, range(6))) == \
            [0, 1, 4, 9, 16, 25]
        assert list(pool.imap(_square, range(6))) == \
            [0, 1, 4, 9, 16, 25]
        assert pool.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]


def test_joblib_backend(re_cluster):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = joblib.Parallel()(
            joblib.delayed(_square)(i) for i in range(8))
    assert out == [i * i for i in range(8)]
