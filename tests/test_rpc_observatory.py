"""Control-plane load observatory coverage: the bounded server-side
RPC accounting table (per-handler + per-caller with a hard talker
cap), event-loop lag probes (blocked-loop detection feeding the
``event_loop_lag`` default alert through the history store, fire ->
resolve), pubsub/KV amplification accounting, the hotrpc CLI
renderer — and the tier-1 e2e: handler-table parity against the live
dispatch dict, dead-subscriber pruning on worker death, and the CLI /
debug-bundle surfaces serving the same snapshot."""

import asyncio
import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import rpc_stats
from ray_tpu.util.rpc_stats import (AmplificationStats, LoopLagProbe,
                                    OVERFLOW_KEY, ServerStats)


# ---------------------------------------------------------------------------
# server-side accounting units (no cluster)
# ---------------------------------------------------------------------------


def test_server_stats_accounting_and_percentiles():
    st = ServerStats()
    for _ in range(9):
        st.record("kv_put", "worker", 0.0001, 0.002, recv_bytes=100,
                  reply_bytes=10)
    st.record("kv_put", "worker", 0.0001, 0.9, recv_bytes=100, ok=False)
    st.record("ping", "driver", 0.0, 0.0001)
    snap = st.snapshot()
    rows = {r["method"]: r for r in snap["methods"]}
    kv = rows["kv_put"]
    assert kv["calls"] == 10 and kv["errors"] == 1
    assert kv["recv_bytes"] == 1000 and kv["reply_bytes"] == 90
    # p50 sits in the low-ms buckets; p99 reaches the slow outlier.
    assert kv["handler_p50_s"] <= 0.01
    assert kv["handler_p99_s"] >= 0.5
    assert kv["handler_max_s"] == pytest.approx(0.9)
    # Methods sort by total handler time: the hot one leads.
    assert snap["methods"][0]["method"] == "kv_put"
    talkers = {(t["method"], t["caller"]): t for t in snap["talkers"]}
    assert talkers[("kv_put", "worker")]["calls"] == 10
    assert talkers[("ping", "driver")]["calls"] == 1


def test_server_stats_parity_preregistration():
    """register_methods() seeds zero rows so the accounting table
    covers the full dispatch dict before any traffic."""
    st = ServerStats()
    st.register_methods(["a", "b", "c"])
    st.record("b", "worker", 0.0, 0.001)
    assert st.methods() == ["a", "b", "c"]
    rows = {r["method"]: r for r in st.snapshot()["methods"]}
    assert rows["a"]["calls"] == 0 and rows["b"]["calls"] == 1


def test_server_stats_talker_cap_overflow():
    """The talker table has a HARD entry cap: distinct (method, caller)
    keys beyond it fold into one __other__ row instead of growing."""
    st = ServerStats(entry_cap=8)
    for i in range(50):
        st.record(f"m{i}", "worker", 0.0, 0.001)
    snap = st.snapshot()
    # 8 real rows + the single __other__ fold row.
    assert len(snap["talkers"]) == 8 + 1
    assert snap["overflow"] == 50 - 8
    other = {(t["method"], t["caller"]): t
             for t in snap["talkers"]}[OVERFLOW_KEY]
    assert other["calls"] == snap["overflow"]
    # Per-method rows are NOT capped (method names are code-bounded).
    assert len(snap["methods"]) == 50


def test_caller_kind_classification():
    class FakeConn:
        def __init__(self, name="", state=None):
            self.name = name
            self.state = state if state is not None else {}

    assert rpc_stats.caller_kind(
        FakeConn(state={"caller_kind": "worker"})) == "worker"
    assert rpc_stats.caller_kind(FakeConn(name="worker-head")) == "head"
    assert rpc_stats.caller_kind(FakeConn(name="peer-1234")) == "peer"
    assert rpc_stats.caller_kind(object()) == "peer"


def test_amplification_stats_snapshot():
    amp = AmplificationStats()
    amp.record_publish("actor_state", fanout=3, nbytes=100)
    amp.record_publish("actor_state", fanout=5, nbytes=100, pruned=2)
    amp.record_prune("actor_state", 1)
    amp.record_kv_put("metrics", nbytes=1000, fanout=1)
    amp.record_kv_put("functions", nbytes=500, fanout=0)
    snap = amp.snapshot()
    (ch,) = snap["pubsub"]
    assert ch["channel"] == "actor_state" and ch["publishes"] == 2
    assert ch["messages"] == 8 and ch["bytes"] == 800
    assert ch["drops_pruned"] == 3 and ch["fanout"] == 5
    assert ch["fanout_avg"] == pytest.approx(4.0)
    kv = {r["ns"]: r for r in snap["kv"]}
    # metrics ns: every byte is written once and delivered once more.
    assert kv["metrics"]["amplification"] == pytest.approx(2.0)
    assert kv["functions"]["amplification"] == pytest.approx(1.0)
    assert snap["pruned_total"] == 3


# ---------------------------------------------------------------------------
# event-loop lag probe (own loop, no cluster)
# ---------------------------------------------------------------------------


def _loop_in_thread():
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    return loop, thread


def test_loop_lag_probe_detects_blocked_loop():
    from ray_tpu.util import flight_recorder, telemetry

    assert telemetry.enabled()
    loop, _thread = _loop_in_thread()
    probe = LoopLagProbe(loop, "obs-unit-loop", interval_s=0.02,
                         stall_threshold_s=0.2).start()
    try:
        time.sleep(0.15)  # healthy ticks first
        healthy = probe.summary()
        assert healthy["ticks"] >= 2 and healthy["stalls"] == 0
        loop.call_soon_threadsafe(time.sleep, 0.5)  # starve the loop
        time.sleep(0.8)
        s = probe.summary()
        assert s["lag_max_s"] >= 0.3, s
        assert s["stalls"] >= 1
        assert s["lag_p99_s"] > s["lag_p50_s"]
        # The stall left its flight-recorder evidence trail.
        events = [e for e in flight_recorder.snapshot()
                  if e["subsystem"] == "rpc"
                  and e["event"] == "loop_stall"
                  and e["tags"].get("loop") == "obs-unit-loop"]
        assert events, "loop stall must land in the flight ring"
        # And the telemetry histogram carries the observation.
        m = telemetry.metric("ray_tpu_event_loop_lag_seconds")
        key = (("proc", probe.tag),)
        vec = m._hists.get(key)
        assert vec is not None and vec[-1] >= s["ticks"] - 1
    finally:
        probe.stop()
        loop.call_soon_threadsafe(loop.stop)


def test_install_probe_idempotent_and_replaces_dead_loop():
    loop, _thread = _loop_in_thread()
    try:
        p1 = rpc_stats.install_probe(loop, "obs-idem", interval_s=0.05)
        p2 = rpc_stats.install_probe(loop, "obs-idem", interval_s=0.05)
        assert p1 is p2, "same live loop: one probe"
        assert any(s["loop"] == "obs-idem"
                   for s in rpc_stats.probe_summaries())
    finally:
        loop.call_soon_threadsafe(loop.stop)
    # Old loop stopped (init/shutdown churn): a new loop under the same
    # name takes over instead of leaking a dead probe.
    time.sleep(0.1)
    loop2, _t2 = _loop_in_thread()
    try:
        p3 = rpc_stats.install_probe(loop2, "obs-idem",
                                     interval_s=0.05)
        assert p3 is not p1 and p3.loop is loop2
    finally:
        p3.stop()
        loop2.call_soon_threadsafe(loop2.stop)


def test_loop_lag_alert_fires_and_resolves():
    """The satellite e2e: a blocked loop's probe observations flow
    through the (push-shaped) history store and trip the shipped
    ``event_loop_lag`` default rule, then resolve once the stall ages
    out of the rule's window."""
    from ray_tpu.util import alerts
    from ray_tpu.util import metrics as um
    from ray_tpu.util.alerts import AlertEngine
    from ray_tpu.util.metrics_history import MetricsHistoryStore

    rule = next(r for r in alerts.default_rules()
                if r.name == "event_loop_lag")
    assert rule.metric == "ray_tpu_event_loop_lag_seconds"

    loop, _thread = _loop_in_thread()
    probe = rpc_stats.install_probe(loop, "obs-alert-loop",
                                    interval_s=0.02,
                                    stall_threshold_s=0.2)
    assert probe is not None, "metrics plane must be live in tests"
    st = MetricsHistoryStore()
    engine = AlertEngine(st, rules=[rule], clock=lambda: 0.0)

    def push(ts):
        snap = {k: v for k, v in um.local_snapshot().items()
                if k == rule.metric}
        st.ingest("p1", snap, ts=ts)

    try:
        time.sleep(0.1)
        push(1000.0)  # seeds the cumulative baseline
        loop.call_soon_threadsafe(time.sleep, 0.6)  # wedge the loop
        time.sleep(1.0)
        push(1010.0)  # the stall tick lands as a window delta
        assert engine.evaluate(now=1011.0) == []   # breach -> pending
        trans = engine.evaluate(now=1017.0)        # for_s elapsed
        fired = [t for t in trans if t["event"] == "fired"
                 and t["episode"]["rule"] == "event_loop_lag"]
        assert fired, f"lag alert never fired: {trans}"
        assert fired[0]["episode"]["tags"]["proc"] == probe.tag
        assert fired[0]["episode"]["evidence"]
        # Healthy again: the stall delta ages out of the 60 s window
        # and the rule resolves by absence (histograms do not carry
        # forward).
        trans = engine.evaluate(now=1100.0)
        assert [t["event"] for t in trans
                if t["episode"]["rule"] == "event_loop_lag"] \
            == ["resolved"]
    finally:
        probe.stop()
        loop.call_soon_threadsafe(loop.stop)


# ---------------------------------------------------------------------------
# CLI renderer (pure, no cluster)
# ---------------------------------------------------------------------------


def test_render_hotrpc_lines():
    from ray_tpu.scripts.cli import _render_hotrpc

    snap = {
        "since_s": 12.0, "entry_cap": 512, "overflow": 0,
        "methods": [
            {"method": "kv_put", "calls": 40, "errors": 1,
             "handler_s": 0.4, "handler_p50_s": 0.002,
             "handler_p99_s": 0.09, "handler_max_s": 0.12,
             "queue_wait_p99_s": 0.001, "recv_bytes": 4096,
             "reply_bytes": 512},
            {"method": "idle_handler", "calls": 0, "errors": 0,
             "handler_s": 0.0},
        ],
        "talkers": [{"method": "kv_put", "caller": "worker",
                     "calls": 40, "handler_s": 0.4,
                     "recv_bytes": 4096}],
        "loops": [{"loop": "ray-tpu-head", "proc": "1/ray-tpu-head",
                   "interval_s": 0.25, "ticks": 100,
                   "lag_avg_s": 0.001, "lag_max_s": 0.4,
                   "lag_p50_s": 0.001, "lag_p99_s": 0.3,
                   "stalls": 2}],
        "loop_lag_cluster": [{"tags": {"proc": "9/worker-loop"},
                              "p50_s": 0.001, "p99_s": 0.25}],
        "amplification": {
            "pubsub": [{"channel": "actor_state", "publishes": 10,
                        "messages": 30, "bytes": 3000,
                        "drops_pruned": 2, "fanout": 3,
                        "fanout_avg": 3.0}],
            "kv": [{"ns": "metrics", "puts": 5, "bytes": 5000,
                    "amplified_bytes": 10000, "amplification": 2.0}],
            "pruned_total": 2,
        },
    }
    text = "\n".join(_render_hotrpc(snap))
    assert "handlers: 2 tracked, 1 active" in text
    assert "kv_put" in text and "90.0ms" in text  # p99
    assert "1 registered handler(s) with no calls yet" in text
    assert "worker" in text
    assert "ray-tpu-head" in text and "stalls=2" in text
    assert "9/worker-loop" in text
    assert "fanout=3" in text and "drops=2" in text
    assert "x2.0" in text  # kv amplification factor
    assert "2 dead subscriber(s)" in text
    # Empty snapshot renders a hint, not a crash.
    empty = "\n".join(_render_hotrpc(
        {"methods": [], "talkers": [], "loops": []}))
    assert "no RPC traffic recorded yet" in empty


# ---------------------------------------------------------------------------
# e2e: parity, pruning, and the surfaces (cluster)
# ---------------------------------------------------------------------------


def _poll(predicate, timeout_s=30.0, interval_s=0.3):
    deadline = time.monotonic() + timeout_s
    while True:
        value = predicate()
        if value:
            return value
        if time.monotonic() > deadline:
            return predicate()
        time.sleep(interval_s)


def test_handler_table_parity_e2e(ray_start_isolated):
    """Every handler in the live GCS dispatch dict appears in the
    accounting table (register_methods parity): a newly added ``h_*``
    cannot dodge instrumentation."""
    from ray_tpu import api
    from ray_tpu.util.state import _call

    handlers = set(api._global_node.service.handlers())
    assert "rpc_stats" in handlers
    snap = _call("rpc_stats", {})
    tracked = {m["method"] for m in snap["methods"]}
    missing = handlers - tracked
    assert not missing, f"handlers missing from accounting: {missing}"


def test_rpc_accounting_and_surfaces_e2e(ray_start_isolated, tmp_path):
    """Drive real traffic, then assert the hotrpc CLI and the debug
    bundle ``rpc/`` section render the SAME snapshot data."""
    from ray_tpu.scripts.cli import _render_hotrpc
    from ray_tpu.util.debug import write_debug_bundle
    from ray_tpu.util.state import _call

    @ray_tpu.remote(num_cpus=1)
    def nop(i):
        return i

    assert ray_tpu.get([nop.remote(i) for i in range(20)],
                       timeout=300) == list(range(20))

    # The head's loop probe arms 0.5 s after loop start and ticks every
    # probe interval — poll until it has at least one observation.
    def probe_ticking():
        snap = _call("rpc_stats", {"top": 10})
        if any(lp["loop"] == "ray-tpu-head" and lp["ticks"] > 0
               for lp in snap["loops"]):
            return snap
        return None

    snap = _poll(probe_ticking, timeout_s=15.0)
    assert snap, "head loop-lag probe never ticked"
    rows = {m["method"]: m for m in snap["methods"]}
    assert rows["task_done"]["calls"] >= 20
    assert rows["task_done"]["handler_p99_s"] > 0.0
    assert rows["task_done"]["recv_bytes"] > 0
    callers = {t["caller"] for t in snap["talkers"]}
    assert "worker" in callers, snap["talkers"]
    # Queue wait is accounted separately from handler time.
    assert rows["task_done"]["queue_wait_p99_s"] >= 0.0

    # The CLI renderer accepts the live snapshot.
    text = "\n".join(_render_hotrpc(snap, top=10))
    assert "task_done" in text and "handlers:" in text

    # The debug bundle's rpc/ section carries the same data shape.
    out = str(tmp_path / "bundle")
    manifest = write_debug_bundle(out, profile_duration_s=0,
                                  trace_duration_s=0)
    assert "rpc" in manifest, manifest.get("errors")
    assert manifest["rpc"]["methods"] >= len(snap["methods"])
    with open(os.path.join(out, "rpc", "stats.json")) as f:
        dumped = json.load(f)
    assert {m["method"] for m in snap["methods"]} \
        <= {m["method"] for m in dumped["methods"]}
    assert dumped["talkers"] and dumped["loops"]
    assert "amplification" in dumped


def test_dead_subscriber_pruned_e2e(ray_start_isolated):
    """A subscriber whose worker dies is PRUNED from the fan-out set
    (and counted), instead of being notified forever."""
    from ray_tpu.util.state import _call

    @ray_tpu.remote(num_cpus=0.01)
    class Sub:
        def subscribe(self):
            from ray_tpu.util.state import _call as call

            call("subscribe", {"channel": "obs-prune"})
            return 1

    s = Sub.remote()
    assert ray_tpu.get(s.subscribe.remote(), timeout=120) == 1
    snap = _call("rpc_stats", {})
    before = snap["amplification"]["pruned_total"]
    ray_tpu.kill(s)

    def pruned():
        _call("publish", {"channel": "obs-prune", "data": {"x": 1}})
        snap = _call("rpc_stats", {})
        amp = snap["amplification"]
        return amp if amp["pruned_total"] > before else None

    amp = _poll(pruned, timeout_s=30.0)
    assert amp, "dead subscriber never pruned"
    # After the prune the channel fans out to nobody. (If the
    # worker-death path pruned before any publish, the channel row may
    # not exist at all — publishes to an empty set early-return.)
    _call("publish", {"channel": "obs-prune", "data": {"x": 2}})
    snap = _call("rpc_stats", {})
    ch = {c["channel"]: c
          for c in snap["amplification"]["pubsub"]}.get("obs-prune")
    assert ch is None or ch["fanout"] == 0
