"""LogTailer edge paths (core/log_monitor.py): rotation/truncation
restart, partial-line carry across polls, the per-poll byte cap, and
the worker-*.log filename filter. Pure filesystem tests — no cluster."""

import os

from ray_tpu.core.log_monitor import MAX_BYTES_PER_POLL, LogTailer


def _write(path, data, mode="ab"):
    with open(path, mode) as f:
        f.write(data)


def test_poll_returns_new_complete_lines(tmp_path):
    t = LogTailer(str(tmp_path))
    p = tmp_path / "worker-abc123.log"
    _write(p, b"one\ntwo\n")
    out = t.poll()
    assert out == [("abc123", ["one", "two"])]
    # Nothing new: no entry at all (not an empty one).
    assert t.poll() == []
    _write(p, b"three\n")
    assert t.poll() == [("abc123", ["three"])]


def test_partial_line_carried_across_polls(tmp_path):
    t = LogTailer(str(tmp_path))
    p = tmp_path / "worker-w.log"
    _write(p, b"head\npart")
    assert t.poll() == [("w", ["head"])]
    # The unterminated tail is held, not emitted as a broken line.
    _write(p, b"ial\ntail\n")
    assert t.poll() == [("w", ["partial", "tail"])]


def test_rotation_restart_when_file_shrinks(tmp_path):
    """size < offset means the file was rotated/truncated in place:
    the tailer restarts from 0 instead of silently going quiet."""
    t = LogTailer(str(tmp_path))
    p = tmp_path / "worker-w.log"
    _write(p, b"old line one\nold line two\n")
    assert t.poll() == [("w", ["old line one", "old line two"])]
    _write(p, b"new\n", mode="wb")  # rotation: shorter fresh content
    assert t.poll() == [("w", ["new"])]


def test_truncation_to_empty_then_regrow(tmp_path):
    t = LogTailer(str(tmp_path))
    p = tmp_path / "worker-w.log"
    _write(p, b"before\n")
    assert t.poll() == [("w", ["before"])]
    _write(p, b"", mode="wb")       # truncated to zero
    assert t.poll() == []           # size == offset(0): nothing yet
    _write(p, b"after\n")
    assert t.poll() == [("w", ["after"])]


def test_per_poll_byte_cap(tmp_path):
    """A worker spamming output cannot wedge a poll: each poll reads at
    most MAX_BYTES_PER_POLL per file and catches up on later polls
    without losing or splitting lines."""
    t = LogTailer(str(tmp_path))
    p = tmp_path / "worker-w.log"
    line = b"x" * 99 + b"\n"        # 100 bytes/line
    total = (MAX_BYTES_PER_POLL // 100) + 50
    _write(p, line * total)
    first = t.poll()[0][1]
    assert len(first) < total       # capped, not one giant read
    # The cap lands mid-line; the fragment must carry, not emit.
    assert all(len(ln) == 99 for ln in first)
    got = len(first)
    while True:
        out = t.poll()
        if not out:
            break
        assert all(len(ln) == 99 for ln in out[0][1])
        got += len(out[0][1])
    assert got == total             # nothing lost across capped polls


def test_only_worker_log_files_are_tailed(tmp_path):
    t = LogTailer(str(tmp_path))
    _write(tmp_path / "worker-ok.log", b"yes\n")
    _write(tmp_path / "other.log", b"no\n")
    _write(tmp_path / "worker-ok.txt", b"no\n")
    _write(tmp_path / "head.log", b"no\n")
    out = t.poll()
    assert out == [("ok", ["yes"])]


def test_missing_directory_is_quiet():
    assert LogTailer("/nonexistent/logs/dir").poll() == []
