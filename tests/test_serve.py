"""Tests for ray_tpu.serve (reference strategy: python/ray/serve/tests/
test_api.py, test_autoscaling_policy.py, test_batching.py)."""

import asyncio
import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    ray_tpu.init(num_cpus=6, num_tpus=0)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _cleanup_apps(serve_cluster):
    yield
    # Tests normally delete their own apps; a failed assertion must not
    # leak replicas (and their CPU) into the rest of the module.
    leftover = {key.split("#", 1)[0] for key in serve.status()}
    for app in leftover:
        serve.delete(app)


@serve.deployment
class Echo:
    def __call__(self, x):
        return {"echo": x}

    def shout(self, x):
        return str(x).upper()


def test_deploy_and_handle(serve_cluster):
    h = serve.run(Echo.bind(), name="echo_app", proxy=False)
    assert h.remote("hi").result() == {"echo": "hi"}
    assert h.options(method_name="shout").remote("hi").result() == "HI"
    assert h.shout.remote("abc").result() == "ABC"
    serve.delete("echo_app")


def test_function_deployment(serve_cluster):
    @serve.deployment
    def square(x):
        return x * x

    h = serve.run(square.bind(), name="fn_app", proxy=False)
    assert h.remote(7).result() == 49
    serve.delete("fn_app")


def test_multi_replica_routing(serve_cluster):
    @serve.deployment(num_replicas=3, num_cpus=0.1)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    h = serve.run(WhoAmI.bind(), name="who", proxy=False)
    pids = {h.remote(None).result() for _ in range(30)}
    assert len(pids) >= 2  # pow-2 routing spreads load
    serve.delete("who")


def test_composition(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment(num_cpus=0.1)
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        async def __call__(self, x):
            resp = self.doubler.remote(x)
            doubled = await resp
            return doubled + 1

    h = serve.run(Ingress.bind(Doubler.bind()), name="comp", proxy=False)
    assert h.remote(10).result() == 21
    serve.delete("comp")


def test_user_config_reconfigure(serve_cluster):
    @serve.deployment(user_config={"mult": 3}, num_cpus=0.1)
    class Mult:
        def __init__(self):
            self.mult = 1

        def reconfigure(self, cfg):
            self.mult = cfg["mult"]

        def __call__(self, x):
            return x * self.mult

    h = serve.run(Mult.bind(), name="mult", proxy=False)
    assert h.remote(5).result() == 15
    serve.delete("mult")


def test_http_proxy(serve_cluster):
    @serve.deployment
    class Api:
        def __call__(self, request):
            data = request.json()
            return {"sum": data["a"] + data["b"], "path": request.path}

    serve.run(Api.bind(), name="http_app", route_prefix="/calc",
              http_port=18713)
    body = json.dumps({"a": 2, "b": 40}).encode()
    req = urllib.request.Request(
        "http://127.0.0.1:18713/calc", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.loads(resp.read())
    assert out == {"sum": 42, "path": "/calc"}
    # routes endpoint
    with urllib.request.urlopen(
            "http://127.0.0.1:18713/-/routes", timeout=30) as resp:
        routes = json.loads(resp.read())
    assert routes.get("/calc") == "http_app#Api"
    # health
    with urllib.request.urlopen(
            "http://127.0.0.1:18713/-/healthz", timeout=30) as resp:
        assert resp.read() == b"success"
    serve.delete("http_app")


def test_batching(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def __call__(self, xs):
            self.batch_sizes.append(len(xs))
            return [x * 10 for x in xs]

        def get_sizes(self):
            return self.batch_sizes

    h = serve.run(Batched.bind(), name="batched", proxy=False)
    responses = [h.remote(i) for i in range(16)]
    results = [r.result(timeout=60) for r in responses]
    assert results == [i * 10 for i in range(16)]
    sizes = h.get_sizes.remote().result()
    assert max(sizes) > 1  # requests actually batched
    serve.delete("batched")


def test_batch_drops_cancelled_requests_at_flush():
    """A request cancelled while parked in the batch queue is dropped
    at flush time — never executed for a dead client — and queue wait
    is observed in serve_batch_queue_wait_seconds."""
    from ray_tpu.util import telemetry

    telemetry.reset_for_testing()
    executed = []

    @serve.batch(max_batch_size=10, batch_wait_timeout_s=0.2)
    async def fn(xs):
        executed.extend(xs)
        return [x * 2 for x in xs]

    async def main():
        t1 = asyncio.ensure_future(fn(1))
        t2 = asyncio.ensure_future(fn(2))
        await asyncio.sleep(0.05)  # both parked, flush pending
        t1.cancel()
        assert await t2 == 4
        with pytest.raises(asyncio.CancelledError):
            await t1

    try:
        asyncio.run(main())
        assert executed == [2], executed
        m = telemetry.metric("ray_tpu_serve_batch_queue_wait_seconds")
        # Only the surviving request's wait is observed.
        assert sum(h[-1] for h in m._hists.values()) == 1, m._hists
    finally:
        telemetry.reset_for_testing()


def test_batch_all_cancelled_skips_execution():
    executed = []

    @serve.batch(max_batch_size=10, batch_wait_timeout_s=0.1)
    async def fn(xs):
        executed.extend(xs)
        return xs

    async def main():
        tasks = [asyncio.ensure_future(fn(i)) for i in range(3)]
        await asyncio.sleep(0.02)
        for t in tasks:
            t.cancel()
        await asyncio.sleep(0.3)  # flush timer fires on an empty batch

    asyncio.run(main())
    assert executed == [], "batch ran for exclusively dead clients"


def test_batch_never_exceeds_max_batch_size():
    """A same-tick burst larger than max_batch_size must reach the batch
    fn in <= max_batch_size slices — XLA executables are compiled/padded
    for the declared max, so the bound is a hard contract."""
    sizes = []

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    async def fn(xs):
        sizes.append(len(xs))
        return [x + 1 for x in xs]

    async def main():
        # All 20 submits land in one event-loop tick, before any
        # detached flush task gets to run.
        return await asyncio.gather(*[fn(i) for i in range(20)])

    results = asyncio.run(main())
    assert results == [i + 1 for i in range(20)]
    assert sum(sizes) == 20
    assert max(sizes) <= 4, sizes


def test_autoscaling_up(serve_cluster):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1,
            upscale_delay_s=0.5, downscale_delay_s=60),
        num_cpus=0.1)
    class Slow:
        async def __call__(self, _):
            await asyncio.sleep(0.8)
            return "ok"

    h = serve.run(Slow.bind(), name="auto", proxy=False)
    status = serve.status()["auto#Slow"]
    assert status["running_replicas"] == 1
    # Flood with concurrent requests; autoscaler should add replicas.
    responses = [h.remote(i) for i in range(12)]
    deadline = time.time() + 30
    scaled = False
    while time.time() < deadline:
        st = serve.status()["auto#Slow"]
        if st["target_replicas"] > 1:
            scaled = True
            break
        time.sleep(0.5)
    assert scaled, "autoscaler did not scale up"
    for r in responses:
        assert r.result(timeout=60) == "ok"
    serve.delete("auto")


def test_scale_from_zero(serve_cluster):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=0, max_replicas=2, target_ongoing_requests=2,
            upscale_delay_s=0.1),
        num_cpus=0.1)
    class Cold:
        def __call__(self, x):
            return x + 1

    h = serve.run(Cold.bind(), name="cold", proxy=False)
    assert serve.status()["cold#Cold"]["running_replicas"] == 0
    # First request triggers scale-from-zero and eventually completes.
    assert h.remote(41).result(timeout=90) == 42
    serve.delete("cold")


def test_multiplexed(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "weight": len(model_id)}

        async def __call__(self, x):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return x * model["weight"]

    h = serve.run(MultiModel.bind(), name="mm", proxy=False)
    r1 = h.options(multiplexed_model_id="ab").remote(10).result()
    assert r1 == 20
    r2 = h.options(multiplexed_model_id="abcd").remote(10).result()
    assert r2 == 40
    # cached: second call to same model id shouldn't reload
    h.options(multiplexed_model_id="ab").remote(1).result()
    serve.delete("mm")


def test_status_and_redeploy(serve_cluster):
    @serve.deployment(num_cpus=0.1)
    class V:
        def __call__(self, _):
            return 1

    serve.run(V.bind(), name="redeploy", proxy=False)
    assert "redeploy#V" in serve.status()

    @serve.deployment(name="V", num_cpus=0.1)
    class V2:
        def __call__(self, _):
            return 2

    h = serve.run(V2.bind(), name="redeploy", proxy=False)
    assert h.remote(None).result() == 2
    serve.delete("redeploy")
    assert "redeploy#V" not in serve.status()


def test_grpc_ingress(serve_cluster):
    """The gRPC ingress routes to the same deployments as HTTP
    (reference: proxy.py:542 gRPCProxy)."""
    import pickle

    import grpc

    from ray_tpu import serve
    from ray_tpu.serve.api import PROXY_NAME

    @serve.deployment
    class GEcho:
        def __call__(self, payload):
            return {"got": payload}

    serve.run(GEcho.bind(), name="gapp", route_prefix="/gapp")
    proxy = ray_tpu.get_actor(PROXY_NAME)
    port = ray_tpu.get(proxy.get_grpc_port.remote(), timeout=60)
    assert port
    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    call = ch.unary_unary("/ray_tpu.serve.UserDefinedService/gapp")
    out = pickle.loads(call(pickle.dumps((("ping",), {})), timeout=60))
    assert out == {"got": "ping"}
    # Unknown route -> NOT_FOUND, not a hang.
    bad = ch.unary_unary("/ray_tpu.serve.UserDefinedService/nope")
    try:
        bad(pickle.dumps(((), {})), timeout=30)
        assert False, "expected NOT_FOUND"
    except grpc.RpcError as e:
        assert e.code() == grpc.StatusCode.NOT_FOUND
    ch.close()


def test_grpc_ingress_auth(serve_cluster):
    """Hardening (VERDICT r4 #10): non-loopback binds require a shared
    secret; with a token set, unauthenticated calls are rejected with
    UNAUTHENTICATED before the pickle payload is touched."""
    import pickle

    import grpc
    import pytest as _pytest

    from ray_tpu.serve.grpc_proxy import GrpcProxy

    # A wide bind without a token must refuse to start.
    with _pytest.raises(ValueError, match="non-loopback"):
        GrpcProxy(lambda: None, host="0.0.0.0", port=0)

    # Token-protected loopback ingress end to end.
    from ray_tpu import serve
    from ray_tpu.serve.router import Router

    @serve.deployment
    class SEcho:
        def __call__(self, payload):
            return {"ok": payload}

    serve.run(SEcho.bind(), name="sapp", route_prefix="/sapp",
              proxy=False)
    from ray_tpu.serve.api import CONTROLLER_NAME

    router = Router(ray_tpu.get_actor(CONTROLLER_NAME))
    gp = GrpcProxy(lambda: router, host="127.0.0.1", port=0,
                   token="sekrit")
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{gp.port}")
        call = ch.unary_unary("/ray_tpu.serve.UserDefinedService/sapp")
        payload = pickle.dumps((("x",), {}))
        with _pytest.raises(grpc.RpcError) as ei:
            call(payload, timeout=30)
        assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
        with _pytest.raises(grpc.RpcError) as ei:
            call(payload, timeout=30,
                 metadata=(("serve-token", "wrong"),))
        assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
        out = pickle.loads(call(
            payload, timeout=60, metadata=(("serve-token", "sekrit"),)))
        assert out == {"ok": "x"}
        ch.close()
    finally:
        gp.stop()


def test_delete_then_immediate_redeploy(serve_cluster):
    """Generation-stamped replica names: a redeploy right after delete
    must not adopt a replica that is mid graceful-shutdown (r5
    advisor)."""
    @serve.deployment(num_cpus=0.1)
    class V:
        def __call__(self, x):
            return f"v2:{x}"

    @serve.deployment(num_cpus=0.1, name="V")
    class V1:
        def __call__(self, x):
            return f"v1:{x}"

    h = serve.run(V1.bind(), name="gen_app", proxy=False)
    assert h.remote(1).result() == "v1:1"
    serve.delete("gen_app")
    h2 = serve.run(V.bind(), name="gen_app", proxy=False)
    assert h2.remote(2).result() == "v2:2"
    serve.delete("gen_app")


# -- deployment scheduler (replica placement) --------------------------------


def test_deployment_scheduler_policies():
    from ray_tpu.serve.scheduler import DeploymentScheduler

    nodes = ["a", "b", "c"]
    # SPREAD: least-loaded first, deterministic tie-break.
    d = DeploymentScheduler("SPREAD").choose_node(
        nodes, {"a": 2, "b": 1, "c": 1})
    assert d.node_id == "b" and d.eligible
    # PACK: busiest first.
    d = DeploymentScheduler("PACK").choose_node(
        nodes, {"a": 2, "b": 1})
    assert d.node_id == "a"
    # Cap filters nodes; all-full -> ineligible.
    d = DeploymentScheduler("SPREAD", max_replicas_per_node=2).choose_node(
        nodes, {"a": 2, "b": 2, "c": 1})
    assert d.node_id == "c"
    d = DeploymentScheduler("SPREAD", max_replicas_per_node=1).choose_node(
        ["a"], {"a": 1})
    assert not d.eligible
    # DEFAULT without cap defers to the cluster scheduler.
    d = DeploymentScheduler("DEFAULT").choose_node(nodes, {})
    assert d.node_id is None and d.eligible
    with pytest.raises(ValueError):
        DeploymentScheduler("DIAGONAL")
    with pytest.raises(ValueError):
        DeploymentScheduler("SPREAD", max_replicas_per_node=0)


def test_replicas_spread_across_nodes(serve_cluster):
    from ray_tpu import api

    # Two extra virtual nodes (reference: cluster_utils fake nodes).
    api._global_node.add_node({"CPU": 4.0})
    api._global_node.add_node({"CPU": 4.0})

    @serve.deployment(num_replicas=4, num_cpus=0.1)
    class Where:
        def __call__(self, _):
            return "ok"

    serve.run(Where.bind(), name="spread_app")
    # Inspect controller-side placement state.
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    deadline = time.time() + 60
    per_node = {}
    while time.time() < deadline:
        snap = ray_tpu.get(ctrl.get_routing_snapshot.remote(), timeout=30)
        key = "spread_app#Where"
        row = snap["table"].get(key, {})
        if len(row.get("replica_names", [])) >= 4:
            reply = ray_tpu.get(
                ctrl.get_replica_nodes.remote(key), timeout=30)
            if len(reply) == 4 and all(reply.values()):
                per_node = {}
                for node in reply.values():
                    per_node[node] = per_node.get(node, 0) + 1
                break
        time.sleep(0.5)
    assert per_node, "replicas never resolved their nodes"
    # 4 replicas over 3 nodes, SPREAD: max 2 on any one node.
    assert max(per_node.values()) <= 2, per_node
    assert len(per_node) >= 2, per_node
    serve.delete("spread_app")


def test_max_replicas_per_node_caps(serve_cluster):
    from ray_tpu import api

    # Self-sufficient: ensure >= 3 schedulable nodes regardless of what
    # other tests in this module did to the shared cluster.
    while len(ray_tpu.nodes()) < 3:
        api._global_node.add_node({"CPU": 4.0})

    @serve.deployment(num_replicas=3, num_cpus=0.1,
                      max_replicas_per_node=1)
    class Capped:
        def __call__(self, _):
            return "ok"

    serve.run(Capped.bind(), name="capped_app")
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    key = "capped_app#Capped"
    deadline = time.time() + 60
    reply = {}
    while time.time() < deadline:
        reply = ray_tpu.get(
            ctrl.get_replica_nodes.remote(key), timeout=30)
        if len(reply) == 3 and all(reply.values()):
            break
        time.sleep(0.5)
    assert len(reply) == 3 and all(reply.values()), reply
    per_node = {}
    for node in reply.values():
        per_node[node] = per_node.get(node, 0) + 1
    assert max(per_node.values()) == 1, per_node
    serve.delete("capped_app")
