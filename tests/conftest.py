"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh (the driver's
dry-run does the same): JAX_PLATFORMS / XLA_FLAGS must be set before jax
imports anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize registers the axon (tunneled TPU) PJRT
# plugin in every interpreter and force-sets jax_platforms="axon,cpu".
# Tests run on a virtual 8-device CPU mesh; override after import.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the dominant suite cost is re-jitting the
# same tiny models in every test process; cache compiled executables
# across tests AND across suite runs. Keyed by a machine fingerprint:
# XLA:CPU AOT results are ISA-specific, and a cache written on another
# host class loads with "could lead to SIGILL" warnings and then
# crashes/wedges workers mid-test.
import hashlib as _hashlib
import platform as _platform

_fingerprint = _platform.machine()
try:
    with open("/proc/cpuinfo") as _f:
        # Only the ISA flags LINE: later fields (cpu MHz, bogomips)
        # vary between boots/reads and would defeat the cache.
        _fingerprint += _f.read().split("flags", 1)[1].split("\n", 1)[0]
except (OSError, IndexError):
    pass
_machine_tag = _hashlib.sha256(
    _fingerprint.encode()).hexdigest()[:10]
_cache_dir = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__),
                 f".jit_cache_{_machine_tag}"))
# Env (not jax.config) so spawned worker processes inherit the cache.
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

# Concurrency lint plane: the whole suite (including the chaos lanes)
# runs with witness-instrumented locks (util/locks.py) so cross-thread
# lock-order inversions are detected at acquire time. Non-strict —
# an inversion is recorded to the flight recorder (lockdep/inversion)
# and logged at ERROR instead of raised — so a real finding surfaces in
# logs/debug dumps without flaking unrelated tests. The witness unit
# tests opt back into strict mode explicitly.
os.environ.setdefault("RAY_TPU_LOCKDEP", "1")
os.environ.setdefault("RAY_TPU_LOCKDEP_STRICT", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="module")
def ray_start():
    """Module-scoped cluster: 4 CPUs, no TPU (workers are plain processes)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_isolated():
    """Function-scoped cluster for tests that mutate cluster state."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()
