"""GCS fault tolerance: durable control-plane state across head restarts.

Reference: redis-backed GCS restart (src/ray/gcs/store_client/
redis_store_client.h behind gcs_table_storage.h:242) and worker-side
re-registration (node_manager.cc:1122 HandleNotifyGCSRestart). Here the
store is sqlite in the session dir; a head restarted on the same session
dir reloads KV / detached actors / placement groups and recreates the
detached actors on fresh workers."""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu


_head_starts = [0]


def _start_head(port, session_dir):
    _head_starts[0] += 1
    path = os.path.join(session_dir, f"head_stdout_{_head_starts[0]}.log")
    log = open(path, "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.head_main",
         "--port", str(port), "--num-cpus", "4",
         "--session-dir", session_dir,
         "--object-store-memory", str(128 << 20)],
        stdout=log, stderr=subprocess.STDOUT,
    )
    log.close()
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        with open(path, "rb") as f:
            if b"listening" in f.read():
                return proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"head exited: {open(path, 'rb').read()[-2000:]}")
        time.sleep(0.2)
    raise RuntimeError(f"head never listened: "
                       f"{open(path, 'rb').read()[-2000:]}")


def _dump_session(session_dir):
    """Diagnostics on failure: head output + worker logs (also copied to
    /tmp/persist_fail_dump.txt so truncated captures keep the evidence)."""
    out = []
    for root, _, files in os.walk(session_dir):
        for name in files:
            if name.endswith(".log"):
                p = os.path.join(root, name)
                try:
                    with open(p, "rb") as f:
                        out.append(f"==== {p} ====\n"
                                   f"{f.read()[-8000:].decode(errors='replace')}")
                except OSError:
                    pass
    text = "\n".join(out)
    try:
        with open("/tmp/persist_fail_dump.txt", "w") as f:
            f.write(text)
    except OSError:
        pass
    return text


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_gcs_storage_roundtrip(tmp_path):
    from ray_tpu.core.gcs_storage import GcsStorage

    st = GcsStorage(str(tmp_path / "gcs.sqlite"))
    st.put("kv", "a", ("ns", b"k", b"v"))
    st.put("kv", "b", ("ns", b"k2", b"v2"))
    st.delete("kv", "b")
    st.close()
    st2 = GcsStorage(str(tmp_path / "gcs.sqlite"))
    assert st2.get("kv", "a") == ("ns", b"k", b"v")
    assert st2.get("kv", "b") is None
    assert dict(st2.items("kv")) == {"a": ("ns", b"k", b"v")}
    st2.close()


def test_head_restart_recovers_state(tmp_path):
    """SIGKILL the head; restart on the same port + session dir; a new
    driver session resolves the detached named actor (recreated on a
    fresh worker), reads back KV, and completes a queued PG."""
    port = _free_port()
    session_dir = str(tmp_path / "session")
    os.makedirs(session_dir, exist_ok=True)
    head = _start_head(port, session_dir)
    try:
        ray_tpu.init(address=f"127.0.0.1:{port}")

        @ray_tpu.remote(lifetime="detached", name="survivor",
                        max_restarts=-1)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        try:
            assert ray_tpu.get(c.bump.remote(), timeout=120) == 1
        except Exception:
            print(_dump_session(session_dir))
            raise
        ray_tpu.kv_put(b"persist-key", b"persist-value")
        pg = ray_tpu.placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.ready(timeout=120)
        ray_tpu.shutdown()

        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)

        head = _start_head(port, session_dir)
        ray_tpu.init(address=f"127.0.0.1:{port}")
        # KV survived.
        assert ray_tpu.kv_get(b"persist-key") == b"persist-value"
        # The detached actor was recreated on a fresh worker; its handle
        # resolves by name and serves calls (in-memory state reset — the
        # restart is a restart, not a resurrection).
        c2 = ray_tpu.get_actor("survivor")
        assert ray_tpu.get(c2.bump.remote(), timeout=180) == 1
        # A placement group created before the crash completes again.
        from ray_tpu.util import state as state_api

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            pgs = state_api.list_placement_groups()
            if any(p["state"] == "CREATED" for p in pgs):
                break
            time.sleep(0.5)
        assert any(p["state"] == "CREATED" for p in pgs)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        head.kill()
        head.wait(timeout=30)


def test_serve_survives_head_restart(tmp_path):
    """VERDICT r4 #3: controller fault tolerance. kill -9 the head,
    restart on the same port + session dir — the recreated controller
    recovers checkpointed app specs from GCS KV and the app serves
    again WITHOUT redeploy (reference:
    serve/_private/application_state.py checkpoint/recover)."""
    import urllib.request

    port = _free_port()
    http_port = _free_port()
    session_dir = str(tmp_path / "session")
    os.makedirs(session_dir, exist_ok=True)
    head = _start_head(port, session_dir)
    try:
        ray_tpu.init(address=f"127.0.0.1:{port}")
        from ray_tpu import serve

        @serve.deployment(num_cpus=0.1)
        class Hello:
            def __call__(self, request):
                return "hello-ft"

        serve.run(Hello.bind(), name="ft_app", route_prefix="/hello",
                  http_port=http_port)

        def fetch(timeout=20):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/hello",
                    timeout=timeout) as r:
                return r.read().decode().strip('"')

        assert fetch() == "hello-ft"
        ray_tpu.shutdown()

        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)
        head = _start_head(port, session_dir)

        # No redeploy: the recreated controller + proxy must converge on
        # their own from the KV checkpoint.
        deadline = time.monotonic() + 300
        last_err = None
        while time.monotonic() < deadline:
            try:
                if fetch(timeout=5) == "hello-ft":
                    break
            except Exception as e:
                last_err = e
                time.sleep(1.0)
        else:
            print(_dump_session(session_dir))
            raise AssertionError(
                f"app never came back after head restart: {last_err}")
    finally:
        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)
