"""Cluster health plane coverage: the bounded metrics time-series
store (seeding/delta semantics, downsampling, the hard byte cap), the
SLO alert engine lifecycle under a fake clock, the merge-staleness
surfaces, the CLI renderers, the timeline alerts lane — and the tier-1
e2e: a FaultInjector-era breaker trip AND a stalled train rank raise
two distinct alerts that fire with series-window evidence and resolve
after the fault clears, visible through ``ray_tpu alerts`` and the
debug bundle."""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.util.alerts import AlertEngine, AlertRule
from ray_tpu.util.metrics_history import MetricsHistoryStore


# ---------------------------------------------------------------------------
# history store units (no cluster)
# ---------------------------------------------------------------------------


def _counter(value, tags=()):
    return {"type": "counter", "description": "",
            "values": [[list(tags), value]]}


def _gauge(value, tags=()):
    return {"type": "gauge", "description": "",
            "values": [[list(tags), value]]}


def _hist(vec, boundaries, tags=()):
    return {"type": "histogram", "description": "",
            "boundaries": list(boundaries),
            "hists": [[list(tags), list(vec)]]}


def test_counter_first_snapshot_seeds_without_append():
    st = MetricsHistoryStore()
    # A process's pre-history cumulative count is not a burst.
    assert st.ingest("p1", {"c": _counter(100.0)}, ts=1000.0) == 0
    assert st.point_count() == 0
    # The next push appends the increment (plus the series-birth zero
    # point, so window deltas over the series' birth are exact).
    assert st.ingest("p1", {"c": _counter(105.0)}, ts=1002.0) == 1
    rows = st.window_agg("c", "delta", 60.0, now=1003.0)
    assert len(rows) == 1 and rows[0]["value"] == pytest.approx(5.0)
    rows = st.window_agg("c", "rate", 60.0, now=1003.0)
    assert rows[0]["value"] == pytest.approx(5.0 / 60.0)


def test_counter_new_series_from_known_proc_is_real_increment():
    st = MetricsHistoryStore()
    st.ingest("p1", {"a": _counter(7.0)}, ts=1000.0)  # seeds the proc
    # A key newly appearing from a KNOWN proc is a real increment
    # from zero, not pre-history.
    assert st.ingest("p1", {"a": _counter(7.0),
                            "b": _counter(3.0)}, ts=1002.0) == 1
    rows = st.window_agg("b", "delta", 60.0, now=1003.0)
    assert rows[0]["value"] == pytest.approx(3.0)


def test_counter_restart_uses_raw_value():
    st = MetricsHistoryStore()
    st.ingest("p1", {"c": _counter(100.0)}, ts=1000.0)
    st.ingest("p1", {"c": _counter(105.0)}, ts=1002.0)
    # Cumulative value went DOWN: the proc restarted; its new raw
    # count is the increment.
    st.ingest("p1", {"c": _counter(2.0)}, ts=1004.0)
    rows = st.window_agg("c", "delta", 60.0, now=1005.0)
    assert rows[0]["value"] == pytest.approx(7.0)


def test_unchanged_snapshot_appends_nothing():
    """O(changed series): an idle cluster's re-pushes cost zero
    points."""
    st = MetricsHistoryStore()
    snap = {"c": _counter(10.0), "g": _gauge(4.0)}
    st.ingest("p1", snap, ts=1000.0)
    before = st.point_count()
    assert st.ingest("p1", snap, ts=1002.0) == 0
    assert st.point_count() == before


def test_gauge_change_only_and_carry_forward():
    st = MetricsHistoryStore(staleness_s=15.0)
    assert st.ingest("p1", {"g": _gauge(1.0)}, ts=1000.0) == 1
    assert st.ingest("p1", {"g": _gauge(1.0)}, ts=1002.0) == 0
    # No point falls inside the 5 s window, but the writer is still
    # fresh: the last-known value carries forward.
    rows = st.window_agg("g", "max", 5.0, now=1010.0)
    assert rows[0]["value"] == pytest.approx(1.0)
    assert st.window_agg("g", "avg", 5.0, now=1010.0)[0]["value"] \
        == pytest.approx(1.0)
    # Past the staleness horizon a dead writer's gauge is NOT
    # presented as current.
    assert st.window_agg("g", "max", 5.0, now=1100.0) == []


def test_gauge_goes_stale_when_proc_gone():
    st = MetricsHistoryStore(staleness_s=15.0)
    st.ingest("p1", {"g": _gauge(2.0)}, ts=1000.0)
    st.on_proc_gone("p1")
    # No carry-forward for a departed writer: once its points age out
    # of the window, the gauge is simply absent, not "still 2.0".
    assert st.window_agg("g", "last", 5.0, now=1010.0) == []
    assert st.index()[0]["fresh"] is False


def test_histogram_percentile_from_window_bucket_delta():
    st = MetricsHistoryStore()
    bounds = [0.1, 1.0]
    # vec layout: per-bucket counts [<=0.1, <=1.0, +Inf], sum, count.
    st.ingest("p1", {"h": _hist([0, 0, 0, 0.0, 0], bounds)}, ts=1000.0)
    st.ingest("p1", {"h": _hist([4, 4, 0, 2.0, 8], bounds)}, ts=1002.0)
    p50 = st.window_agg("h", "p50", 60.0, now=1003.0)[0]["value"]
    assert p50 == pytest.approx(0.1)  # rank 4 tops out bucket 1
    p99 = st.window_agg("h", "p99", 60.0, now=1003.0)[0]["value"]
    assert p99 == pytest.approx(0.1 + 0.9 * (7.92 - 4) / 4)
    assert st.window_agg("h", "delta", 60.0, now=1003.0)[0]["value"] \
        == pytest.approx(8.0)
    # query_points renders the cumulative observation count.
    pts = st.query_points("h", 60.0, now=1003.0)[0]["points"]
    assert pts[-1][1] == pytest.approx(8.0)


def test_downsampling_coarse_ring_extends_recent():
    st = MetricsHistoryStore(recent_points=8, coarse_points=64,
                             coarse_interval_s=10.0)
    for i in range(30):
        st.ingest("p1", {"g": _gauge(float(i))}, ts=1000.0 + 10.0 * i)
    pts = st.query_points("g", 1e6, now=1300.0)[0]["points"]
    # The fine ring alone holds 8 points; the coarse ring splices
    # older history in front of it.
    assert len(pts) > 8
    assert pts[0][0] < pts[-8][0]
    assert pts == sorted(pts, key=lambda p: p[0])


def test_memory_hard_cap_evicts_instead_of_growing():
    st = MetricsHistoryStore(max_bytes=8192)
    for i in range(300):
        st.ingest("p1", {"g": {
            "type": "gauge", "description": "",
            "values": [[[["i", str(i)]], float(i)]],
        }}, ts=1000.0 + i)
    assert st.evictions > 0
    assert st.bytes_used <= st.max_bytes
    assert st.series_count() < 300
    # Survivors are the most recently updated series.
    names = {s["tags"]["i"] for s in st.index()}
    assert "299" in names and "0" not in names


def test_per_metric_series_cap_evicts_oldest():
    """High-cardinality protection: one metric flooding distinct tag
    sets evicts its own oldest series at the per-metric cap instead of
    crowding every other metric out of the byte budget."""
    from ray_tpu.util import telemetry

    st = MetricsHistoryStore(max_series_per_metric=8)
    st.ingest("p1", {"innocent": _gauge(1.0)}, ts=999.0)
    for i in range(40):
        st.ingest("p1", {"hot": {
            "type": "gauge", "description": "",
            "values": [[[["i", str(i)]], float(i)]],
        }}, ts=1000.0 + i)
    hot = [s for s in st.index() if s["name"] == "hot"]
    assert len(hot) == 8
    assert st.cap_evictions == 40 - 8
    # Survivors are the newest tag sets; the flood victimized only its
    # own metric.
    tags = {s["tags"]["i"] for s in hot}
    assert "39" in tags and "0" not in tags
    assert any(s["name"] == "innocent" for s in st.index())
    # The eviction pressure is observable.
    assert st.snapshot()["cap_evictions"] == 32
    m = telemetry.metric("ray_tpu_metrics_history_series_capped_total")
    assert m._values.get((), 0) >= 32


def test_eviction_keeps_proc_baselines():
    """Diff baselines survive series eviction, so a re-created series
    resumes correct deltas instead of re-counting history."""
    st = MetricsHistoryStore(max_bytes=4096)
    st.ingest("p1", {"c": _counter(100.0)}, ts=1000.0)
    st.ingest("p1", {"c": _counter(110.0)}, ts=1001.0)
    for i in range(200):  # flood: evicts the counter series
        st.ingest("p1", {"g": {
            "type": "gauge", "description": "",
            "values": [[[["i", str(i)]], 1.0]],
        }}, ts=1002.0 + i)
    assert st.evictions > 0
    st.ingest("p1", {"c": _counter(115.0)}, ts=1300.0)
    rows = st.window_agg("c", "delta", 60.0, now=1301.0)
    assert rows and rows[0]["value"] == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# alert engine units (fake clock, no cluster)
# ---------------------------------------------------------------------------


def _gauge_rule(**kw):
    base = dict(name="r", metric="ray_tpu_gcs_nodes", agg="max",
                op=">", threshold=0.5, window_s=5.0, for_s=0.0,
                tags={"state": "SUSPECT"})
    base.update(kw)
    return AlertRule(**base)


def test_engine_pending_for_s_then_fire_then_resolve():
    st = MetricsHistoryStore(staleness_s=15.0)
    engine = AlertEngine(st, rules=[_gauge_rule(for_s=5.0)],
                         clock=lambda: 0.0)
    tags = (("state", "SUSPECT"),)
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(1.0, tags)}, ts=1000.0)
    assert engine.evaluate(now=1001.0) == []      # breach -> pending
    assert engine.evaluate(now=1003.0) == []      # sustain not met
    trans = engine.evaluate(now=1006.5)           # for_s=5 elapsed
    assert [t["event"] for t in trans] == ["fired"]
    ep = trans[0]["episode"]
    assert ep["rule"] == "r" and ep["resolved_ts"] is None
    assert ep["evidence"], "fired episode must carry series evidence"
    assert engine.firing() and engine.firing()[0]["tags"] == dict(tags)
    # Recovery: the gauge drops and the old high point has aged out of
    # the 5 s window — the carry-forward value (0) stops breaching.
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(0.0, tags)}, ts=1007.0)
    trans = engine.evaluate(now=1008.0)
    assert [t["event"] for t in trans] == ["resolved"]
    assert trans[0]["episode"]["resolved_ts"] == 1008.0
    assert engine.firing() == []
    # state() serves episodes newest first with the full lifecycle.
    state = engine.state()
    assert state["enabled"] and state["episodes"][0]["rule"] == "r"
    assert state["episodes"][0]["resolved_ts"] == 1008.0


def test_engine_stays_firing_while_breach_in_window():
    """A gauge dropping back does not resolve the alert until the high
    point ages out of the rule's window — max is over the window, not
    the instant."""
    st = MetricsHistoryStore(staleness_s=60.0)
    engine = AlertEngine(st, rules=[_gauge_rule()], clock=lambda: 0.0)
    tags = (("state", "SUSPECT"),)
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(2.0, tags)}, ts=1000.0)
    assert [t["event"] for t in engine.evaluate(now=1001.0)] == ["fired"]
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(0.0, tags)}, ts=1002.0)
    assert engine.evaluate(now=1003.0) == []  # 2.0 still in the window
    assert engine.firing()
    trans = engine.evaluate(now=1008.0)       # high point aged out
    assert [t["event"] for t in trans] == ["resolved"]


def test_engine_counter_rule_resolves_when_delta_ages_out():
    st = MetricsHistoryStore()
    rule = AlertRule("cb", "ray_tpu_circuit_breaker_transitions_total",
                     "delta", ">=", 1.0, window_s=5.0, for_s=0.0,
                     tags={"state": "open"})
    engine = AlertEngine(st, rules=[rule], clock=lambda: 0.0)
    tags = (("state", "open"),)
    name = "ray_tpu_circuit_breaker_transitions_total"
    st.ingest("p1", {name: _counter(0.0, tags)}, ts=1000.0)
    st.ingest("p1", {name: _counter(1.0, tags)}, ts=1001.0)
    trans = engine.evaluate(now=1001.5)
    assert [t["event"] for t in trans] == ["fired"]
    # No new opens: the window empties and the rule resolves by
    # absence (counters do not carry forward).
    trans = engine.evaluate(now=1010.0)
    assert [t["event"] for t in trans] == ["resolved"]


def test_engine_flight_recorder_and_telemetry_on_transition():
    from ray_tpu.util import flight_recorder, telemetry

    st = MetricsHistoryStore()
    engine = AlertEngine(st, rules=[_gauge_rule()], clock=lambda: 0.0)
    tags = (("state", "SUSPECT"),)
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(3.0, tags)}, ts=1000.0)
    engine.evaluate(now=1001.0)
    events = [e for e in flight_recorder.snapshot()
              if e["subsystem"] == "alert" and e["event"] == "fired"
              and e["tags"].get("rule") == "r"]
    assert events, "fire must land in the flight ring"
    assert json.loads(events[-1]["tags"]["window"]), "evidence window"
    m = telemetry.metric("ray_tpu_alerts_transitions_total")
    assert m._values.get((("rule", "r"), ("state", "fired")), 0) >= 1
    g = telemetry.metric("ray_tpu_alerts_firing")
    assert g._values.get((("rule", "r"),)) == 1


def test_remove_rule_drops_states():
    st = MetricsHistoryStore()
    engine = AlertEngine(st, rules=[_gauge_rule()], clock=lambda: 0.0)
    tags = (("state", "SUSPECT"),)
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(3.0, tags)}, ts=1000.0)
    engine.evaluate(now=1001.0)
    engine.remove_rule("r")
    assert engine.firing() == []
    assert engine.evaluate(now=1002.0) == []


# ---------------------------------------------------------------------------
# merge staleness, CLI renderers, timeline lane (no cluster)
# ---------------------------------------------------------------------------


def test_merge_snapshots_freshest_gauge_wins_and_stale_flagged():
    from ray_tpu.util import metrics as um

    now = 10_000.0
    fresh = {"_meta": {"ts": now - 1.0, "pid": 1},
             "g": _gauge(1.0), "c": _counter(5.0)}
    stale = {"_meta": {"ts": now - 300.0, "pid": 2},
             "g": _gauge(2.0), "c": _counter(7.0)}
    # KV iteration order must NOT decide: the stale proc sorts LAST
    # (so last-write-wins would pick it) yet the fresh value wins.
    merged, procs, stale_map = um.merge_snapshots(
        {"metrics:a_fresh": fresh, "metrics:z_stale": stale},
        now=now, staleness_s=15.0)
    assert merged["g"]["values"][()] == 1.0
    assert merged["c"]["values"][()] == 12.0  # counters still sum
    by_proc = {p["proc"]: p for p in procs}
    assert by_proc["metrics:z_stale"]["stale"] is True
    assert by_proc["metrics:a_fresh"]["stale"] is False
    assert by_proc["metrics:a_fresh"]["age_s"] == pytest.approx(1.0)
    assert "g" not in stale_map  # freshest writer is inside the window
    # Only stale writers left -> the series itself is flagged.
    merged, _procs, stale_map = um.merge_snapshots(
        {"metrics:z_stale": stale}, now=now, staleness_s=15.0)
    assert stale_map == {"g": [()]}
    text = um.render_prometheus(merged, procs=_procs, stale=stale_map)
    assert "# ray_tpu snapshot metrics:z_stale" in text
    assert "STALE" in text


def test_sparkline_renderer():
    from ray_tpu.scripts.cli import _SPARK_CHARS, _sparkline

    assert _sparkline([]) == ""
    flat = _sparkline([3.0, 3.0, 3.0])
    assert len(set(flat)) == 1 and len(flat) == 3
    ramp = _sparkline(list(range(8)), width=8)
    assert ramp[0] == _SPARK_CHARS[0] and ramp[-1] == _SPARK_CHARS[-1]
    assert len(_sparkline(list(range(1000)), width=60)) == 60


def test_render_history_lines():
    from ray_tpu.scripts.cli import _render_history

    assert _render_history({"enabled": False}, 600)[0].startswith(
        "metrics history disabled")
    assert "no history" in _render_history(
        {"enabled": True, "name": "x", "series": []}, 600)[0]
    reply = {
        "enabled": True, "name": "m",
        "series": [{"tags": {"rank": "0"}, "kind": "gauge",
                    "fresh": False,
                    "points": [[1.0, 0.0], [2.0, 4.0], [3.0, 2.0]]}],
        "agg": "max",
        "aggregates": [{"tags": {"rank": "0"}, "value": 4.0}],
    }
    lines = _render_history(reply, 600)
    text = "\n".join(lines)
    assert "{rank=0} (gauge, 3 points)  [STALE]" in text
    assert "min=0 max=4 last=2" in text
    assert "max[600s]{rank=0} = 4" in text


def test_render_alerts_lines():
    from ray_tpu.scripts.cli import _render_alerts

    assert _render_alerts({"enabled": False})[0].startswith(
        "alert engine disabled")
    reply = {
        "enabled": True,
        "firing": [{"rule": "stall", "tags": {"rank": "0"},
                    "value": 31.5, "fired_ts": 1000.0,
                    "severity": "error"}],
        "episodes": [
            {"rule": "stall", "tags": {"rank": "0"}, "value": 31.5,
             "fired_ts": 1000.0, "resolved_ts": None,
             "evidence": [[999.0, 10.0], [1000.0, 31.5]]},
            {"rule": "cb", "tags": {}, "value": 1.0,
             "fired_ts": 900.0, "resolved_ts": 950.0, "evidence": []},
        ],
        "rules": [{"name": "stall"}, {"name": "cb"}],
    }
    text = "\n".join(_render_alerts(reply))
    assert "FIRING (1):" in text and "[ERROR] stall {rank=0}" in text
    assert "STILL FIRING" in text
    assert "cb" in text and "resolved" in text
    assert "rules: 2 loaded (stall, cb)" in text


def test_alert_trace_events_lane():
    from ray_tpu.util.timeline import alert_trace_events

    events = alert_trace_events([
        {"rule": "a", "metric": "m", "tags": {"x": "1"}, "value": 2.0,
         "threshold": 1.0, "severity": "warn",
         "fired_ts": 100.0, "resolved_ts": 103.0},
        {"rule": "b", "metric": "m", "tags": {}, "value": 5.0,
         "threshold": 1.0, "severity": "error",
         "fired_ts": 110.0, "resolved_ts": None},
    ])
    assert all(ev["tid"] == "alerts" and ev["cat"] == "alerts"
               for ev in events)
    span, instant = events
    assert span["ph"] == "X" and span["dur"] == pytest.approx(3e6)
    assert span["args"]["series"] == "x=1"
    assert instant["ph"] == "i"  # an open alert stays visible


def test_profiler_bucket_carries_model_id():
    """@serve.multiplexed attribution: the replica pushes model_id into
    the thread context; the sampler's per-request buckets carry it."""
    from ray_tpu.util import profiler

    token = profiler.push_thread_context(
        serve_request="req-1", name="serve:dep", deployment="dep",
        model_id="model-a")
    try:
        counts, tasks = {}, {}
        profiler._sweep(counts, tasks, skip_ident=None)
        assert tasks["req-1"]["model_id"] == "model-a"
        assert tasks["req-1"]["samples"] >= 1
        # The stack root stays serve:<deployment> — attribution rides
        # the bucket labels, not the flame root.
        assert any(k.startswith("serve:dep;") for k in counts)
    finally:
        profiler.pop_thread_context(token)


# ---------------------------------------------------------------------------
# experiment-state journal (satellite: history + open alerts survive a
# head restart)
# ---------------------------------------------------------------------------


def test_history_snapshot_restore_round_trip():
    st = MetricsHistoryStore()
    now = time.time()
    ctags = (("state", "FINISHED"),)
    bounds = [0.1, 1.0, 10.0]
    st.ingest("p1", {"ray_tpu_tasks_total": _counter(5.0, ctags)},
              ts=now - 30)
    st.ingest("p1", {"ray_tpu_tasks_total": _counter(9.0, ctags),
                     "ray_tpu_gcs_nodes": _gauge(3.0)}, ts=now - 20)
    st.ingest("p1", {"ray_tpu_train_step_seconds":
                     _hist([0, 2, 0, 0, 2], bounds)}, ts=now - 15)
    st.ingest("p1", {"ray_tpu_train_step_seconds":
                     _hist([0, 5, 1, 0, 6], bounds)}, ts=now - 5)
    snap = json.loads(json.dumps(st.snapshot(), default=str))

    st2 = MetricsHistoryStore()
    assert st2.restore(snap) > 0
    # Counter window delta survives the round trip.
    assert st2.window_agg("ray_tpu_tasks_total", "delta",
                          60.0)[0]["value"] == 4.0
    # Histogram boundaries rode the snapshot: percentiles still work.
    p90 = st2.window_agg("ray_tpu_train_step_seconds", "p90", 60.0)
    assert p90 and 0.1 <= p90[0]["value"] <= 10.0
    # Continuity: the restarted head's first push from a proc seeds its
    # baseline; the second continues the restored merged counter value
    # instead of double-counting the pre-restart total.
    st2.ingest("p1", {"ray_tpu_tasks_total": _counter(12.0, ctags)},
               ts=now)
    st2.ingest("p1", {"ray_tpu_tasks_total": _counter(15.0, ctags)},
               ts=now + 1)
    pts = st2.query_points("ray_tpu_tasks_total", 600.0,
                           tags=dict(ctags))[0]["points"]
    assert pts[-1][1] == 7.0  # 4 pre-restart + 3 post
    assert [v for _, v in pts] == sorted(v for _, v in pts)


def test_alert_engine_journal_restore_links_episode():
    st = MetricsHistoryStore(staleness_s=60.0)
    engine = AlertEngine(st, rules=[_gauge_rule()])
    tags = (("state", "SUSPECT"),)
    st.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(2.0, tags)},
              ts=1000.0)
    assert [t["event"]
            for t in engine.evaluate(now=1001.0)] == ["fired"]
    data = json.loads(json.dumps(engine.journal_state(), default=str))

    engine2 = AlertEngine(MetricsHistoryStore(),
                          rules=[_gauge_rule()])
    assert engine2.restore(data) == 1
    (f,) = engine2.firing()
    assert f["rule"] == "r" and f["tags"] == dict(tags)
    # The restored firing state resolves against the SAME episode
    # record the journal carried (identity via episode_index), so the
    # episode history shows one fire->resolve lifecycle, not a dangling
    # never-resolved entry.
    trans = engine2.evaluate(now=2000.0)  # empty store: breach gone
    assert [t["event"] for t in trans] == ["resolved"]
    assert list(engine2.episodes)[-1]["resolved_ts"] == 2000.0
    # State machines for rules the new head does not know are dropped;
    # their episode history is kept.
    data2 = dict(data, states=[["ghost_rule", [["a", "b"]],
                                {"state": "firing"}]])
    engine3 = AlertEngine(MetricsHistoryStore(),
                          rules=[_gauge_rule()])
    assert engine3.restore(data2) == 0
    assert len(engine3.episodes) == 1


def test_health_plane_journal_write_and_reload(tmp_path):
    from ray_tpu.core.config import Config
    from ray_tpu.core.health import ClusterHealthPlane

    cfg = Config()
    cfg.health_journal_interval_s = 0.0
    d = str(tmp_path)
    p = ClusterHealthPlane(cfg, session_dir=d)
    tags = (("state", "SUSPECT"),)
    now = time.time()
    p.store.ingest("p1", {"ray_tpu_gcs_nodes": _gauge(2.0, tags)},
                   ts=now)
    p.engine.evaluate(now=now)         # node_suspect -> pending
    p.engine.evaluate(now=now + 5.0)   # for_s=3 elapsed -> fired
    assert any(f["rule"] == "node_suspect"
               for f in p.engine.firing())
    p.maybe_journal()
    jdir = os.path.join(d, "health_journal")
    assert sorted(os.listdir(jdir)) == ["alerts.json", "history.json"]

    # "Head restart": a fresh plane over the same session dir reloads
    # the rings and the open alert, and defers its first evaluation so
    # the restored alert is not insta-resolved before any push arrives.
    p2 = ClusterHealthPlane(cfg, session_dir=d)
    rows = p2.store.query_points("ray_tpu_gcs_nodes", 600.0,
                                 tags=dict(tags))
    assert rows and rows[0]["points"]
    assert any(f["rule"] == "node_suspect"
               for f in p2.engine.firing())
    assert p2._last_eval > time.time()
    p2.maybe_evaluate()  # throttled by the restore hold-off
    assert any(f["rule"] == "node_suspect"
               for f in p2.engine.firing())

    # Journalling disabled: no dir is consulted or created.
    cfg_off = Config()
    cfg_off.health_journal_enabled = False
    p3 = ClusterHealthPlane(cfg_off, session_dir=str(tmp_path / "x"))
    assert p3._journal_dir is None
    p3.maybe_journal()
    assert not os.path.exists(str(tmp_path / "x" / "health_journal"))


# ---------------------------------------------------------------------------
# e2e: breaker trip + stalled rank fire and resolve through the head
# ---------------------------------------------------------------------------


def _poll(predicate, timeout_s=30.0, interval_s=0.5):
    deadline = time.monotonic() + timeout_s
    while True:
        value = predicate()
        if value:
            return value
        if time.monotonic() > deadline:
            return predicate()
        time.sleep(interval_s)


def test_breaker_and_stalled_rank_alerts_e2e(ray_start_isolated,
                                             tmp_path):
    from ray_tpu import train
    from ray_tpu.core.retry import CircuitBreaker
    from ray_tpu.scripts.cli import _render_alerts
    from ray_tpu.train.config import FailureConfig
    from ray_tpu.util import metrics as um
    from ray_tpu.util.state import _call

    # Tight rules so the episode fits test wall-time: a breaker open in
    # the last 3 s, and any rank heartbeat age above 1 s.
    for rule in (
        {"name": "e2e_breaker",
         "metric": "ray_tpu_circuit_breaker_transitions_total",
         "agg": "delta", "op": ">=", "threshold": 1.0,
         "window_s": 3.0, "for_s": 0.0, "tags": {"state": "open"}},
        {"name": "e2e_stall",
         "metric": "ray_tpu_train_step_heartbeat_age_seconds",
         "agg": "max", "op": ">", "threshold": 1.0,
         "window_s": 3.0, "for_s": 0.0, "severity": "error"},
    ):
        reply = _call("alerts_put_rule", rule)
        assert reply["ok"], reply

    # Seed the driver's push baseline in the history store first: a
    # proc's FIRST snapshot deliberately appends nothing.
    um.flush_metrics()

    # Fault 1: FaultInjector-driven breaker open. Partition the task
    # push path so the driver observes real injected faults, and feed
    # those failures into a breaker exactly as the serve router does
    # on replica call failures (retry.py's transition telemetry is the
    # alert's signal either way).
    from ray_tpu.core import rpc as rpc_mod

    fi = rpc_mod.get_fault_injector()
    fi.install("partition", method="push_tasks", direction="send",
               max_matches=2)
    cb = CircuitBreaker(failure_threshold=2, reset_timeout_s=0.5)
    try:
        @ray_tpu.remote
        def victim():
            return 1

        assert ray_tpu.get(victim.remote(), timeout=120) == 1
        assert fi.stats.get("partition", 0) >= 1, "no fault injected"
        for _ in range(2):
            cb.record_failure("replica:faulted")  # -> OPEN transition
    finally:
        fi.reset()
        rpc_mod.reset_fault_injector()
    um.flush_metrics()

    def breaker_fired():
        reply = _call("alerts")
        return any(ep["rule"] == "e2e_breaker"
                   for ep in reply["episodes"]) and reply
    assert _poll(breaker_fired, timeout_s=20.0), \
        "breaker-open alert never fired"

    # Fault 2: rank 0 stalls mid-loop; the gang monitor's heartbeat-age
    # gauge rises until the hang abort, then resets to zero.
    def loop(config):
        for step in range(5):
            if step == 2:
                time.sleep(60)  # wedged device stand-in
            train.report({"step": step})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            name="health_e2e", storage_path=str(tmp_path),
            failure_config=FailureConfig(
                max_failures=0,
                health_check_interval_s=0.25,
                hang_timeout_s=4.0)),
    )
    start = time.monotonic()
    result = trainer.fit()
    assert result.error is not None and "hung" in result.error
    assert time.monotonic() - start < 60.0
    um.flush_metrics()  # ship the post-abort zeroed gauge

    # Both episodes exist and BOTH resolved after the faults cleared
    # (the breaker delta aged out of its window; the stall gauge was
    # reset by the monitor's abort path).
    def both_resolved():
        reply = _call("alerts")
        eps = {ep["rule"]: ep for ep in reply["episodes"]}
        if "e2e_breaker" not in eps or "e2e_stall" not in eps:
            return None
        if not all(eps[r]["resolved_ts"]
                   for r in ("e2e_breaker", "e2e_stall")):
            return None
        return reply
    reply = _poll(both_resolved, timeout_s=40.0)
    assert reply, f"episodes never resolved: {_call('alerts')}"
    eps = {ep["rule"]: ep for ep in reply["episodes"]}
    for name in ("e2e_breaker", "e2e_stall"):
        ep = eps[name]
        assert ep["evidence"], f"{name}: no series-window evidence"
        assert ep["fired_ts"] < ep["resolved_ts"]
    assert eps["e2e_stall"]["tags"].get("rank") == "0"
    assert eps["e2e_stall"]["value"] > 1.0

    # The operator surface shows the episode.
    text = "\n".join(_render_alerts(reply))
    assert "e2e_breaker" in text and "e2e_stall" in text

    # The history store served the evidence series.
    hist = _call("metrics_history", {
        "name": "ray_tpu_train_step_heartbeat_age_seconds",
        "window_s": 600.0, "agg": "max"})
    assert hist["enabled"] and hist["series"]
    assert any(p[1] > 1.0 for s in hist["series"]
               for p in s["points"])

    # And the debug bundle carries the whole episode.
    out = os.path.join(str(tmp_path), "bundle")
    from ray_tpu.util.debug import write_debug_bundle

    manifest = write_debug_bundle(out, profile_duration_s=0)
    assert "history" in manifest and "alerts" in manifest
    with open(os.path.join(out, "history", "series.json")) as f:
        series = json.load(f)
    assert series["series_count"] > 0 and series["series"]
    with open(os.path.join(out, "alerts.json")) as f:
        dumped = json.load(f)
    rules_seen = {ep["rule"] for ep in dumped["episodes"]}
    assert {"e2e_breaker", "e2e_stall"} <= rules_seen
