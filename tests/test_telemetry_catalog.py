"""Tier-1 guard: the built-in ``ray_tpu_*`` metric namespace stays
coherent as instrumentation grows.

Every runtime module must import with metrics enabled (instrumentation
must never break an import), and every ``ray_tpu_``-prefixed metric that
ends up in the registry must come from the telemetry CATALOG with a
lowercase snake_case name and only declared, lowercase tag keys. New
instrumentation that invents a metric outside the catalog — or reuses a
name with a different type — fails here, not in production."""

import importlib
import pkgutil
import re
import warnings

import ray_tpu
from ray_tpu.util import metrics as um
from ray_tpu.util import telemetry

NAME_RE = re.compile(r"^ray_tpu_[a-z0-9]+(_[a-z0-9]+)*$")
TAG_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _walk_module_names():
    for info in pkgutil.walk_packages(ray_tpu.__path__, prefix="ray_tpu."):
        # __main__ modules execute their CLI on import.
        if info.name.rsplit(".", 1)[-1] == "__main__":
            continue
        yield info.name


def test_every_module_imports_with_metrics_enabled():
    assert telemetry.enabled(), (
        "metrics plane disabled in the test environment; the guard "
        "must run with instrumentation live")
    failures = []
    for name in _walk_module_names():
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                importlib.import_module(name)
        except Exception as e:  # noqa: BLE001 — collecting all failures
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "modules failed to import:\n" + "\n".join(failures)


def test_catalog_names_and_tags_conform():
    assert telemetry.CATALOG, "catalog must not be empty"
    for name, (kind, desc, tag_keys, bounds) in telemetry.CATALOG.items():
        assert NAME_RE.match(name), f"bad metric name {name!r}"
        assert name == name.lower()
        assert kind in (telemetry.COUNTER, telemetry.GAUGE,
                        telemetry.HISTOGRAM), name
        assert desc, f"{name} missing description"
        for key in tag_keys:
            assert TAG_RE.match(key), f"{name}: bad tag key {key!r}"
        if kind == telemetry.HISTOGRAM:
            assert bounds and list(bounds) == sorted(bounds), (
                f"{name}: histogram boundaries must be sorted")
        else:
            assert bounds is None, f"{name}: boundaries on non-histogram"
        # Counters follow the Prometheus _total convention; latency
        # histograms the _seconds convention.
        if kind == telemetry.COUNTER:
            assert name.endswith("_total"), name


def test_registry_matches_catalog():
    # Instantiate the full catalog, then lint EVERYTHING ray_tpu_* that
    # any import-time or test-time instrumentation registered.
    telemetry.ensure_all()
    with um._registry_lock:
        registered = dict(um._registry)
    seen = [n for n in registered if n.startswith("ray_tpu_")]
    assert len(seen) >= len(telemetry.CATALOG)
    for name in seen:
        assert name in telemetry.CATALOG, (
            f"metric {name!r} registered outside the telemetry catalog")
        kind, _desc, tag_keys, bounds = telemetry.CATALOG[name]
        m = registered[name]
        assert m.metric_type == kind, (
            f"{name}: registered as {m.metric_type}, catalog says {kind}")
        assert set(m.tag_keys) <= set(tag_keys), (
            f"{name}: undeclared tag keys "
            f"{set(m.tag_keys) - set(tag_keys)}")
        if kind == telemetry.HISTOGRAM:
            assert m.boundaries == sorted(bounds)


def test_train_recovery_metrics_in_catalog():
    """The training-plane recovery metrics (PR: gang health monitoring /
    crash-consistent checkpoints / elastic restart) stay declared — the
    recovery paths emit through these names and a rename/removal would
    silently drop the telemetry."""
    expected = {
        "ray_tpu_train_restarts_total": (telemetry.COUNTER, ("reason",)),
        "ray_tpu_train_hang_detections_total": (telemetry.COUNTER, ()),
        "ray_tpu_train_worker_deaths_total": (telemetry.COUNTER, ()),
        "ray_tpu_train_torn_checkpoint_skips_total": (
            telemetry.COUNTER, ()),
        "ray_tpu_train_elastic_resizes_total": (telemetry.COUNTER, ()),
        "ray_tpu_tune_trial_retries_total": (telemetry.COUNTER, ()),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name


def test_serve_stream_metrics_in_catalog():
    """The serve streaming metrics (TTFT / chunks / aborts) stay
    declared — proxy+router emit through these names and a
    rename/removal would silently blind the streaming plane."""
    expected = {
        "ray_tpu_serve_stream_ttft_seconds": (
            telemetry.HISTOGRAM, ("deployment",)),
        "ray_tpu_serve_stream_chunks_total": (
            telemetry.COUNTER, ("deployment",)),
        "ray_tpu_serve_stream_aborts_total": (
            telemetry.COUNTER, ("deployment", "reason")),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name


def test_serve_engine_and_autoscale_metrics_in_catalog():
    """The continuous-batching engine + autoscaling metrics stay
    declared — the engine loop, the controller's scale decisions, and
    @serve.batch's queue-wait all emit through these names and a
    rename/removal would silently blind the serving plane."""
    expected = {
        "ray_tpu_serve_engine_batch_occupancy": (
            telemetry.GAUGE, ("deployment", "proc")),
        "ray_tpu_serve_engine_queue_depth": (
            telemetry.GAUGE, ("deployment", "proc")),
        "ray_tpu_serve_engine_queue_wait_seconds": (
            telemetry.HISTOGRAM, ("deployment",)),
        "ray_tpu_serve_autoscale_decisions_total": (
            telemetry.COUNTER, ("deployment", "direction", "reason")),
        "ray_tpu_serve_batch_queue_wait_seconds": (
            telemetry.HISTOGRAM, ()),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name


def test_profiler_and_step_heartbeat_metrics_in_catalog():
    """The live-profiling-plane metrics stay declared — the sampler
    (on-demand + continuous) and the gang monitor's device step-counter
    heartbeat emit through these names; a rename/removal would blind
    the profiling plane."""
    expected = {
        "ray_tpu_profiler_samples_total": (
            telemetry.COUNTER, ("mode",)),
        "ray_tpu_profiler_overhead_ratio": (
            telemetry.GAUGE, ("proc",)),
        "ray_tpu_train_step_heartbeat_age_seconds": (
            telemetry.GAUGE, ("rank",)),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name


def test_health_plane_metrics_in_catalog():
    """The cluster-health-plane metrics stay declared — the history
    store's stats/eviction counter and the alert engine's lifecycle
    counters emit through these names; a rename/removal would blind
    the health plane."""
    expected = {
        "ray_tpu_metrics_history_series": (telemetry.GAUGE, ()),
        "ray_tpu_metrics_history_bytes": (telemetry.GAUGE, ()),
        "ray_tpu_metrics_history_evictions_total": (
            telemetry.COUNTER, ()),
        "ray_tpu_alerts_firing": (telemetry.GAUGE, ("rule",)),
        "ray_tpu_alerts_transitions_total": (
            telemetry.COUNTER, ("rule", "state")),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name


def test_device_trace_metrics_in_catalog():
    """The device-trace-plane metrics stay declared — capture() emits
    through these names (capture counter, last-trace-size gauge,
    per-step compile/execute device time); a rename/removal would
    blind the device-trace plane. The ``trace`` flight-recorder
    subsystem is pinned alongside: the capture/failure events are the
    plane's audit trail."""
    expected = {
        "ray_tpu_device_trace_captures_total": (
            telemetry.COUNTER, ("status",)),
        "ray_tpu_device_trace_bytes": (telemetry.GAUGE, ("proc",)),
        "ray_tpu_train_step_device_time_seconds": (
            telemetry.HISTOGRAM, ("rank", "phase")),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name

    from ray_tpu.util import flight_recorder as fr

    assert fr.CATALOG.get("trace") == ("captured", "capture_failed")


def test_control_plane_metrics_in_catalog():
    """The control-plane load-observatory metrics stay declared —
    the per-handler server accounting, the event-loop lag probes, the
    pubsub/KV amplification counters, and the history store's
    per-metric series cap all emit through these names; a
    rename/removal would blind the observatory. The ``loop_stall`` and
    ``subscriber_pruned`` flight events are pinned alongside: they are
    the stall/prune audit trail."""
    expected = {
        "ray_tpu_rpc_server_handler_seconds": (
            telemetry.HISTOGRAM, ("method",)),
        "ray_tpu_rpc_server_queue_wait_seconds": (
            telemetry.HISTOGRAM, ("method",)),
        "ray_tpu_rpc_server_calls_total": (
            telemetry.COUNTER, ("method", "caller")),
        "ray_tpu_rpc_server_errors_total": (
            telemetry.COUNTER, ("method",)),
        "ray_tpu_event_loop_lag_seconds": (
            telemetry.HISTOGRAM, ("proc",)),
        "ray_tpu_pubsub_messages_total": (
            telemetry.COUNTER, ("channel",)),
        "ray_tpu_pubsub_bytes_total": (
            telemetry.COUNTER, ("channel",)),
        "ray_tpu_pubsub_fanout": (telemetry.GAUGE, ("channel",)),
        "ray_tpu_pubsub_dead_subscribers_pruned_total": (
            telemetry.COUNTER, ()),
        "ray_tpu_kv_write_bytes_total": (telemetry.COUNTER, ("ns",)),
        "ray_tpu_kv_write_amplified_bytes_total": (
            telemetry.COUNTER, ("ns",)),
        "ray_tpu_metrics_history_series_capped_total": (
            telemetry.COUNTER, ()),
    }
    for name, (kind, tag_keys) in expected.items():
        assert name in telemetry.CATALOG, name
        got_kind, _desc, got_tags, _bounds = telemetry.CATALOG[name]
        assert got_kind == kind, name
        assert tuple(got_tags) == tag_keys, name

    from ray_tpu.util import flight_recorder as fr

    assert "loop_stall" in fr.CATALOG.get("rpc", ())
    assert "subscriber_pruned" in fr.CATALOG.get("gcs", ())


def test_alert_rules_reference_only_catalog_metrics():
    """Catalog lint extension: every alert rule — the shipped defaults
    and anything constructed through AlertRule/validate_rule — may only
    reference declared catalog metrics and tag keys, with an aggregate
    that fits the metric's kind. A rule naming a typo'd metric fails
    tier-1 here, not silently at evaluation time."""
    import pytest

    from ray_tpu.util import alerts

    rules = alerts.default_rules()
    assert len(rules) >= 8, "default SLO rule set shrank"
    for rule in rules:
        alerts.validate_rule(rule)  # raises on any catalog violation
        spec = telemetry.CATALOG[rule.metric]
        assert rule.agg in alerts.AGGS_BY_KIND[spec[0]], rule.name
        for tag_key in rule.tags:
            assert tag_key in spec[2], (rule.name, tag_key)
    # The mandated default coverage: one rule per pathology class.
    covered = {r.metric for r in rules}
    for metric in (
        "ray_tpu_train_step_heartbeat_age_seconds",
        "ray_tpu_circuit_breaker_transitions_total",
        "ray_tpu_serve_stream_ttft_seconds",
        "ray_tpu_serve_engine_queue_depth",
        "ray_tpu_serve_replica_sheds_total",
        "ray_tpu_gcs_nodes",
        "ray_tpu_object_spilled_bytes_total",
        "ray_tpu_profiler_overhead_ratio",
        "ray_tpu_event_loop_lag_seconds",
        "ray_tpu_rpc_server_handler_seconds",
    ):
        assert metric in covered, f"default rules lost {metric}"
    # And the lint itself has teeth: typo'd metric, undeclared tag,
    # kind-mismatched aggregate all fail validation.
    with pytest.raises(ValueError, match="not in"):
        alerts.validate_rule(alerts.AlertRule(
            "bad", "ray_tpu_does_not_exist_total", "delta", ">", 1.0))
    with pytest.raises(ValueError, match="not declared"):
        alerts.validate_rule(alerts.AlertRule(
            "bad", "ray_tpu_tasks_total", "delta", ">", 1.0,
            tags={"nope": "x"}))
    with pytest.raises(ValueError, match="not valid"):
        alerts.validate_rule(alerts.AlertRule(
            "bad", "ray_tpu_tasks_total", "p99", ">", 1.0))


def test_catalog_metric_roundtrip():
    telemetry.reset_for_testing()
    try:
        telemetry.inc("ray_tpu_tasks_total", 1, {"state": "GUARD_TEST"})
        m = telemetry.metric("ray_tpu_tasks_total")
        assert m._values.get((("state", "GUARD_TEST"),), 0) >= 1
        # Unknown names never create registry entries.
        telemetry.inc("ray_tpu_not_in_catalog_total", 1)
        with um._registry_lock:
            assert "ray_tpu_not_in_catalog_total" not in um._registry
    finally:
        telemetry.reset_for_testing()
