// Multi-threaded stress driver for the shared-memory arena, built to
// run under ThreadSanitizer (reference: the C++ core's TSan/ASan bazel
// configs, .bazelrc tsan/asan — the arena's process-shared mutex, pin
// log, and zombie deferred-free are exactly the code that deserves a
// race detector).
//
// Build + run: bash cpp/tpustore/tsan_check.sh
//
// Threads hammer one arena with the full lifecycle concurrently:
//   writers:  alloc -> fill -> seal          (create/seal state machine)
//   readers:  lookup_pin -> verify -> unpin  (read pins vs eviction)
//   deleters: delete                          (zombie deferred-free)
// A nonzero exit or any TSan report is a failure.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* ts_create(const char* name, uint64_t capacity_bytes);
void ts_detach(void* handle);
int ts_destroy(const char* name);
int64_t ts_alloc(void* handle, const uint8_t* key, uint64_t size,
                 uint64_t* offset_out);
int ts_seal_idx(void* handle, int64_t idx, const uint8_t* key, int guard);
int64_t ts_lookup_pin(void* handle, const uint8_t* key, uint64_t* off,
                      uint64_t* size);
int ts_unpin_read(void* handle, int64_t idx);
int ts_delete(void* handle, const uint8_t* key);
uint64_t ts_used_bytes(void* handle);
uint8_t* ts_base(void* handle);
}

namespace {

constexpr int kKeys = 64;
constexpr uint64_t kObjBytes = 64 * 1024;
constexpr int kItersPerThread = 2000;

void make_key(int i, uint8_t* out) {
  std::memset(out, 0, 20);
  std::memcpy(out, &i, sizeof(i));
}

std::atomic<long> g_errors{0};

void writer(void* h, uint8_t* base, int seed) {
  uint8_t key[20];
  for (int it = 0; it < kItersPerThread; ++it) {
    int i = (seed * 31 + it) % kKeys;
    make_key(i, key);
    uint64_t off = 0;
    int64_t idx = ts_alloc(h, key, kObjBytes, &off);
    if (idx < 0) continue;  // exists / full — fine under contention
    std::memset(base + off, i & 0xff, kObjBytes);
    ts_seal_idx(h, idx, key, /*guard=*/0);
  }
}

void reader(void* h, uint8_t* base, int seed) {
  uint8_t key[20];
  for (int it = 0; it < kItersPerThread; ++it) {
    int i = (seed * 17 + it) % kKeys;
    make_key(i, key);
    uint64_t off = 0, size = 0;
    int64_t idx = ts_lookup_pin(h, key, &off, &size);
    if (idx < 0) continue;
    // While pinned, the payload must be stable and uniform.
    uint8_t first = base[off];
    for (uint64_t j = 0; j < size; j += 4096) {
      if (base[off + j] != first) {
        ++g_errors;
        break;
      }
    }
    ts_unpin_read(h, idx);
  }
}

void deleter(void* h, int seed) {
  uint8_t key[20];
  for (int it = 0; it < kItersPerThread; ++it) {
    int i = (seed * 13 + it) % kKeys;
    make_key(i, key);
    ts_delete(h, key);
  }
}

}  // namespace

int main() {
  const char* name = "rtpu_tsan_stress";
  ts_destroy(name);  // stale from a previous crashed run
  void* h = ts_create(name, 512ull << 20);
  if (!h) {
    std::fprintf(stderr, "ts_create failed\n");
    return 2;
  }
  uint8_t* base = ts_base(h);
  std::vector<std::thread> threads;
  for (int t = 0; t < 3; ++t) {
    threads.emplace_back(writer, h, base, t + 1);
    threads.emplace_back(reader, h, base, t + 5);
  }
  threads.emplace_back(deleter, h, 11);
  threads.emplace_back(deleter, h, 23);
  for (auto& th : threads) th.join();
  long errs = g_errors.load();
  ts_detach(h);
  ts_destroy(name);
  if (errs) {
    std::fprintf(stderr, "payload instability under pins: %ld\n", errs);
    return 1;
  }
  std::puts("tpustore TSan stress: OK");
  return 0;
}
