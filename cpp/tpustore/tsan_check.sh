#!/bin/bash
# ThreadSanitizer gate for the shared-memory arena (reference: the C++
# core's --config=tsan builds). Fails on any data race or stress error.
set -euo pipefail
cd "$(dirname "$0")"
mkdir -p build
g++ -O1 -g -fsanitize=thread -fPIC -std=c++17 -pthread \
    store.cc store_stress.cc -o build/store_stress_tsan -lrt
TSAN_OPTIONS="halt_on_error=1 exitcode=66" ./build/store_stress_tsan
