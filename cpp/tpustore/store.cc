// tpustore: node-wide shared-memory object arena.
//
// Native equivalent of the reference's plasma store
// (src/ray/object_manager/plasma/store.h:55, eviction_policy.h:105,
// dlmalloc.cc): one shared-memory arena per node holding immutable
// sealed objects, allocated from a free-extent allocator with
// boundary coalescing, evicted LRU over unpinned sealed objects.
// Unlike plasma's socket protocol, coordination is in-memory: every
// process on the node maps the same arena and synchronizes on a
// process-shared robust mutex in the arena header. Object payloads are
// mapped zero-copy into clients (host buffers feed jax.device_put
// without a copy).
//
// Exported C API (ctypes-friendly); all functions returning int use
// 0 = ok, negative = error (see TS_E* codes).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x7470757374307245ull;  // "tpust0rE"
constexpr uint32_t kKeyLen = 20;
constexpr uint32_t kEntryCap = 32768;         // max live objects per node
constexpr uint32_t kExtentCap = kEntryCap + 8;
constexpr uint64_t kAlign = 64;

constexpr int TS_OK = 0;
constexpr int TS_EEXIST = -1;
constexpr int TS_ENOENT = -2;
constexpr int TS_EFULL = -3;     // no space even after eviction
constexpr int TS_ETABLE = -4;    // entry table full
constexpr int TS_ESTATE = -5;    // wrong state (e.g. seal of sealed)
constexpr int TS_ESYS = -6;      // system error (shm/mmap)

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Entry {
  uint8_t key[kKeyLen];
  uint64_t offset;
  uint64_t size;
  uint32_t state;
  uint32_t pin;
  uint64_t lru;
};

struct Extent {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;      // arena mapping size
  uint64_t data_offset;     // start of the data area
  uint64_t data_size;
  pthread_mutex_t mutex;
  uint64_t lru_tick;
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t num_evicted;     // stats
  uint32_t num_extents;     // live free extents
  uint32_t pad;
  Entry entries[kEntryCap];
  Extent extents[kExtentCap];  // sorted by offset
};

struct Handle {
  Header* hdr;
  uint64_t map_size;
};

uint64_t AlignUp(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

uint64_t HashKey(const uint8_t* key) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < kKeyLen; i++) {
    h ^= key[i];
    h *= 1099511628211ull;
  }
  return h;
}

class Locker {
 public:
  explicit Locker(Header* hdr) : hdr_(hdr) {
    int rc = pthread_mutex_lock(&hdr_->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; the table is still usable
      // because all mutations below are ordered to be crash-tolerant
      // (worst case: a leaked created-but-unsealed allocation, which
      // eviction of unsealed-stale entries could reclaim later).
      pthread_mutex_consistent(&hdr_->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&hdr_->mutex); }

 private:
  Header* hdr_;
};

// ---- entry table (open addressing, linear probe) ----

Entry* FindEntry(Header* hdr, const uint8_t* key) {
  uint64_t idx = HashKey(key) % kEntryCap;
  for (uint32_t probe = 0; probe < kEntryCap; probe++) {
    Entry* e = &hdr->entries[(idx + probe) % kEntryCap];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTombstone && memcmp(e->key, key, kKeyLen) == 0) {
      return e;
    }
  }
  return nullptr;
}

Entry* FindSlot(Header* hdr, const uint8_t* key) {
  uint64_t idx = HashKey(key) % kEntryCap;
  Entry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kEntryCap; probe++) {
    Entry* e = &hdr->entries[(idx + probe) % kEntryCap];
    if (e->state == kEmpty) return first_tomb ? first_tomb : e;
    if (e->state == kTombstone && !first_tomb) first_tomb = e;
    if (e->state != kTombstone && memcmp(e->key, key, kKeyLen) == 0) {
      return e;  // existing
    }
  }
  return first_tomb;
}

// ---- free-extent allocator (array sorted by offset) ----

int64_t AllocFromExtents(Header* hdr, uint64_t size) {
  for (uint32_t i = 0; i < hdr->num_extents; i++) {
    Extent* ex = &hdr->extents[i];
    if (ex->size >= size) {
      uint64_t off = ex->offset;
      ex->offset += size;
      ex->size -= size;
      if (ex->size == 0) {
        memmove(ex, ex + 1, (hdr->num_extents - i - 1) * sizeof(Extent));
        hdr->num_extents--;
      }
      return static_cast<int64_t>(off);
    }
  }
  return -1;
}

void FreeExtent(Header* hdr, uint64_t offset, uint64_t size) {
  // Insert sorted by offset, then coalesce with neighbors.
  uint32_t pos = 0;
  while (pos < hdr->num_extents && hdr->extents[pos].offset < offset) pos++;
  memmove(&hdr->extents[pos + 1], &hdr->extents[pos],
          (hdr->num_extents - pos) * sizeof(Extent));
  hdr->extents[pos] = {offset, size};
  hdr->num_extents++;
  // Coalesce right.
  if (pos + 1 < hdr->num_extents &&
      hdr->extents[pos].offset + hdr->extents[pos].size ==
          hdr->extents[pos + 1].offset) {
    hdr->extents[pos].size += hdr->extents[pos + 1].size;
    memmove(&hdr->extents[pos + 1], &hdr->extents[pos + 2],
            (hdr->num_extents - pos - 2) * sizeof(Extent));
    hdr->num_extents--;
  }
  // Coalesce left.
  if (pos > 0 && hdr->extents[pos - 1].offset + hdr->extents[pos - 1].size ==
                     hdr->extents[pos].offset) {
    hdr->extents[pos - 1].size += hdr->extents[pos].size;
    memmove(&hdr->extents[pos], &hdr->extents[pos + 1],
            (hdr->num_extents - pos - 1) * sizeof(Extent));
    hdr->num_extents--;
  }
}

void DeleteEntryLocked(Header* hdr, Entry* e) {
  FreeExtent(hdr, e->offset, e->size);
  hdr->used_bytes -= e->size;
  hdr->num_objects--;
  e->state = kTombstone;
  e->pin = 0;
}

// Evict the least-recently-used unpinned sealed object. Returns freed
// bytes, or 0 if nothing evictable.
uint64_t EvictOne(Header* hdr) {
  Entry* victim = nullptr;
  for (uint32_t i = 0; i < kEntryCap; i++) {
    Entry* e = &hdr->entries[i];
    if (e->state == kSealed && e->pin == 0) {
      if (!victim || e->lru < victim->lru) victim = e;
    }
  }
  if (!victim) return 0;
  uint64_t freed = victim->size;
  DeleteEntryLocked(hdr, victim);
  hdr->num_evicted++;
  return freed;
}

}  // namespace

extern "C" {

// Create the arena (head process). Fails if it already exists.
void* ts_create(const char* name, uint64_t capacity_bytes) {
  uint64_t total = sizeof(Header) + AlignUp(capacity_bytes);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = new (mem) Header();
  memset(hdr->entries, 0, sizeof(hdr->entries));
  hdr->total_size = total;
  hdr->data_offset = AlignUp(sizeof(Header));
  hdr->data_size = total - hdr->data_offset;
  hdr->lru_tick = 1;
  hdr->used_bytes = 0;
  hdr->num_objects = 0;
  hdr->num_evicted = 0;
  hdr->num_extents = 1;
  hdr->extents[0] = {hdr->data_offset, hdr->data_size};
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  hdr->magic = kMagic;  // last: attachers spin on magic
  Handle* h = new Handle{hdr, total};
  return h;
}

// Attach to an existing arena (worker processes).
void* ts_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Handle* h = new Handle{hdr, static_cast<uint64_t>(st.st_size)};
  return h;
}

void ts_detach(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  if (!h) return;
  munmap(h->hdr, h->map_size);
  delete h;
}

int ts_destroy(const char* name) { return shm_unlink(name); }

// Allocate space for an object; evicts LRU unpinned sealed objects as
// needed. On success writes the data offset to *out_offset.
int ts_alloc(void* handle, const uint8_t* key, uint64_t size,
             uint64_t* out_offset) {
  Handle* h = static_cast<Handle*>(handle);
  uint64_t need = AlignUp(size);
  if (need > h->hdr->data_size) return TS_EFULL;
  Locker lock(h->hdr);
  Header* hdr = h->hdr;
  Entry* existing = FindEntry(hdr, key);
  if (existing) return TS_EEXIST;
  Entry* slot = FindSlot(hdr, key);
  if (!slot) return TS_ETABLE;
  int64_t off = AllocFromExtents(hdr, need);
  while (off < 0) {
    if (EvictOne(hdr) == 0) return TS_EFULL;
    off = AllocFromExtents(hdr, need);
  }
  memcpy(slot->key, key, kKeyLen);
  slot->offset = static_cast<uint64_t>(off);
  slot->size = need;
  slot->state = kCreated;
  slot->pin = 0;
  slot->lru = hdr->lru_tick++;
  hdr->used_bytes += need;
  hdr->num_objects++;
  *out_offset = slot->offset;
  return TS_OK;
}

int ts_seal(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  if (e->state != kCreated) return TS_ESTATE;
  e->state = kSealed;
  return TS_OK;
}

// Look up a sealed object; bumps its LRU stamp.
int ts_lookup(void* handle, const uint8_t* key, uint64_t* out_offset,
              uint64_t* out_size) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e || e->state != kSealed) return TS_ENOENT;
  e->lru = h->hdr->lru_tick++;
  *out_offset = e->offset;
  *out_size = e->size;
  return TS_OK;
}

int ts_contains(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  return (e && e->state == kSealed) ? 1 : 0;
}

int ts_pin(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  e->pin++;
  return TS_OK;
}

int ts_unpin(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  if (e->pin > 0) e->pin--;
  return TS_OK;
}

int ts_delete(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  DeleteEntryLocked(h->hdr, e);
  return TS_OK;
}

uint8_t* ts_base(void* handle) {
  return reinterpret_cast<uint8_t*>(static_cast<Handle*>(handle)->hdr);
}

uint64_t ts_used_bytes(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  return h->hdr->used_bytes;
}

uint64_t ts_num_objects(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  return h->hdr->num_objects;
}

uint64_t ts_num_evicted(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  return h->hdr->num_evicted;
}

uint64_t ts_capacity(void* handle) {
  return static_cast<Handle*>(handle)->hdr->data_size;
}

}  // extern "C"
