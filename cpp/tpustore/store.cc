// tpustore: node-wide shared-memory object arena.
//
// Native equivalent of the reference's plasma store
// (src/ray/object_manager/plasma/store.h:55, eviction_policy.h:105,
// dlmalloc.cc): one shared-memory arena per node holding immutable
// sealed objects, allocated from a free-extent allocator with
// boundary coalescing, evicted LRU over unpinned sealed objects.
// Unlike plasma's socket protocol, coordination is in-memory: every
// process on the node maps the same arena and synchronizes on a
// process-shared robust mutex in the arena header. Object payloads are
// mapped zero-copy into clients (host buffers feed jax.device_put
// without a copy).
//
// Exported C API (ctypes-friendly); all functions returning int use
// 0 = ok, negative = error (see TS_E* codes).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x7470757374307246ull;  // "tpust0rF" (layout v2)
constexpr uint32_t kKeyLen = 20;
constexpr uint32_t kEntryCap = 32768;         // max live objects per node
constexpr uint32_t kExtentCap = kEntryCap + 8;
constexpr uint32_t kPinLogCap = 8192;         // outstanding read pins
constexpr uint64_t kAlign = 64;

constexpr int TS_OK = 0;
constexpr int TS_EEXIST = -1;
constexpr int TS_ENOENT = -2;
constexpr int TS_EFULL = -3;     // no space even after eviction
constexpr int TS_ETABLE = -4;    // entry table full
constexpr int TS_ESTATE = -5;    // wrong state (e.g. seal of sealed)
constexpr int TS_ESYS = -6;      // system error (shm/mmap)

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
  // Deleted while readers still hold zero-copy views: invisible to
  // lookup/contains/eviction, memory retained until the last read pin
  // drops (plasma never reclaims buffers clients hold,
  // src/ray/object_manager/plasma/object_lifecycle_manager.h:101).
  kZombie = 4,
};

struct Entry {
  uint8_t key[kKeyLen];
  uint64_t offset;
  uint64_t size;
  uint32_t state;
  uint32_t pin;    // read pins: outstanding zero-copy views (+ write hold)
  uint32_t guard;  // eviction guard: owner/primary-copy pins
  uint32_t pad;
  uint64_t lru;
};

struct Extent {
  uint64_t offset;
  uint64_t size;
};

// One outstanding read pin, attributed to the pinning process so pins
// leaked by a crashed reader can be reaped (plasma analog: releasing a
// dead client's object references on disconnect). pid 0 = free slot.
struct PinRec {
  int32_t pid;
  uint32_t idx;  // entry index
};

struct Header {
  uint64_t magic;
  uint64_t total_size;      // arena mapping size
  uint64_t data_offset;     // start of the data area
  uint64_t data_size;
  pthread_mutex_t mutex;
  uint64_t lru_tick;
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t num_evicted;     // stats
  uint32_t num_extents;     // live free extents
  uint32_t pin_log_hint;    // next-free-slot cursor into pin_log
  Entry entries[kEntryCap];
  Extent extents[kExtentCap];  // sorted by offset
  PinRec pin_log[kPinLogCap];
};

struct Handle {
  Header* hdr;
  uint64_t map_size;
};

uint64_t AlignUp(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

uint64_t HashKey(const uint8_t* key) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < kKeyLen; i++) {
    h ^= key[i];
    h *= 1099511628211ull;
  }
  return h;
}

class Locker {
 public:
  explicit Locker(Header* hdr) : hdr_(hdr) {
    int rc = pthread_mutex_lock(&hdr_->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; the table is still usable
      // because all mutations below are ordered to be crash-tolerant
      // (worst case: a leaked created-but-unsealed allocation, which
      // eviction of unsealed-stale entries could reclaim later).
      pthread_mutex_consistent(&hdr_->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&hdr_->mutex); }

 private:
  Header* hdr_;
};

// ---- entry table (open addressing, linear probe) ----
//
// A zombie keeps its slot (its extent is still allocated) but is dead to
// every key-based path: a re-put of the same key inserts a NEW live entry
// further down the probe chain and the two coexist until the zombie's
// last read pin drops. Index-based ops (seal/unpin_read) therefore name
// entries by slot index, never by key.

bool IsLive(const Entry* e) {
  return e->state == kCreated || e->state == kSealed;
}

Entry* FindEntry(Header* hdr, const uint8_t* key) {
  uint64_t idx = HashKey(key) % kEntryCap;
  for (uint32_t probe = 0; probe < kEntryCap; probe++) {
    Entry* e = &hdr->entries[(idx + probe) % kEntryCap];
    if (e->state == kEmpty) return nullptr;
    if (IsLive(e) && memcmp(e->key, key, kKeyLen) == 0) {
      return e;
    }
  }
  return nullptr;
}

Entry* FindSlot(Header* hdr, const uint8_t* key) {
  uint64_t idx = HashKey(key) % kEntryCap;
  Entry* first_free = nullptr;
  for (uint32_t probe = 0; probe < kEntryCap; probe++) {
    Entry* e = &hdr->entries[(idx + probe) % kEntryCap];
    if (e->state == kEmpty) return first_free ? first_free : e;
    if (e->state == kTombstone && !first_free) first_free = e;
    if (IsLive(e) && memcmp(e->key, key, kKeyLen) == 0) {
      return e;  // existing
    }
  }
  return first_free;
}

// ---- free-extent allocator (array sorted by offset) ----

int64_t AllocFromExtents(Header* hdr, uint64_t size) {
  for (uint32_t i = 0; i < hdr->num_extents; i++) {
    Extent* ex = &hdr->extents[i];
    if (ex->size >= size) {
      uint64_t off = ex->offset;
      ex->offset += size;
      ex->size -= size;
      if (ex->size == 0) {
        memmove(ex, ex + 1, (hdr->num_extents - i - 1) * sizeof(Extent));
        hdr->num_extents--;
      }
      return static_cast<int64_t>(off);
    }
  }
  return -1;
}

void FreeExtent(Header* hdr, uint64_t offset, uint64_t size) {
  // Insert sorted by offset, then coalesce with neighbors.
  uint32_t pos = 0;
  while (pos < hdr->num_extents && hdr->extents[pos].offset < offset) pos++;
  memmove(&hdr->extents[pos + 1], &hdr->extents[pos],
          (hdr->num_extents - pos) * sizeof(Extent));
  hdr->extents[pos] = {offset, size};
  hdr->num_extents++;
  // Coalesce right.
  if (pos + 1 < hdr->num_extents &&
      hdr->extents[pos].offset + hdr->extents[pos].size ==
          hdr->extents[pos + 1].offset) {
    hdr->extents[pos].size += hdr->extents[pos + 1].size;
    memmove(&hdr->extents[pos + 1], &hdr->extents[pos + 2],
            (hdr->num_extents - pos - 2) * sizeof(Extent));
    hdr->num_extents--;
  }
  // Coalesce left.
  if (pos > 0 && hdr->extents[pos - 1].offset + hdr->extents[pos - 1].size ==
                     hdr->extents[pos].offset) {
    hdr->extents[pos - 1].size += hdr->extents[pos].size;
    memmove(&hdr->extents[pos], &hdr->extents[pos + 1],
            (hdr->num_extents - pos - 1) * sizeof(Extent));
    hdr->num_extents--;
  }
}

void DeleteEntryLocked(Header* hdr, Entry* e) {
  FreeExtent(hdr, e->offset, e->size);
  hdr->used_bytes -= e->size;
  hdr->num_objects--;
  e->state = kTombstone;
  e->pin = 0;
  e->guard = 0;
}

// ---- read-pin attribution log ----

void PinLogAdd(Header* hdr, uint32_t entry_idx) {
  for (uint32_t probe = 0; probe < kPinLogCap; probe++) {
    PinRec* r = &hdr->pin_log[(hdr->pin_log_hint + probe) % kPinLogCap];
    if (r->pid == 0) {
      r->pid = static_cast<int32_t>(getpid());
      r->idx = entry_idx;
      hdr->pin_log_hint = (hdr->pin_log_hint + probe + 1) % kPinLogCap;
      return;
    }
  }
  // Log full: the pin is still held, just unattributed — a crash of
  // this process then leaks it (pre-reap behavior), nothing worse.
}

void PinLogRemove(Header* hdr, uint32_t entry_idx) {
  int32_t pid = static_cast<int32_t>(getpid());
  for (uint32_t i = 0; i < kPinLogCap; i++) {
    PinRec* r = &hdr->pin_log[i];
    if (r->pid == pid && r->idx == entry_idx) {
      r->pid = 0;
      return;
    }
  }
}

void UnpinEntryLocked(Header* hdr, Entry* e) {
  if (e->pin > 0) e->pin--;
  if (e->pin == 0 && e->state == kZombie) DeleteEntryLocked(hdr, e);
}

// Release read pins recorded by processes that no longer exist, so a
// crashed reader cannot wedge entries forever (plasma frees a dead
// client's references on disconnect). Returns pins released.
uint32_t ReapDeadLocked(Header* hdr) {
  uint32_t reaped = 0;
  int32_t self = static_cast<int32_t>(getpid());
  for (uint32_t i = 0; i < kPinLogCap; i++) {
    PinRec* r = &hdr->pin_log[i];
    if (r->pid == 0 || r->pid == self) continue;
    if (kill(r->pid, 0) != 0 && errno == ESRCH) {
      UnpinEntryLocked(hdr, &hdr->entries[r->idx]);
      r->pid = 0;
      reaped++;
    }
  }
  return reaped;
}

// Evict the least-recently-used sealed object that nobody reads or
// guards. Returns freed bytes, or 0 if nothing evictable.
uint64_t EvictOne(Header* hdr) {
  Entry* victim = nullptr;
  for (uint32_t i = 0; i < kEntryCap; i++) {
    Entry* e = &hdr->entries[i];
    if (e->state == kSealed && e->pin == 0 && e->guard == 0) {
      if (!victim || e->lru < victim->lru) victim = e;
    }
  }
  if (!victim) return 0;
  uint64_t freed = victim->size;
  DeleteEntryLocked(hdr, victim);
  hdr->num_evicted++;
  return freed;
}

}  // namespace

extern "C" {

// Create the arena (head process). Fails if it already exists.
void* ts_create(const char* name, uint64_t capacity_bytes) {
  uint64_t total = sizeof(Header) + AlignUp(capacity_bytes);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = new (mem) Header();
  memset(hdr->entries, 0, sizeof(hdr->entries));
  hdr->total_size = total;
  hdr->data_offset = AlignUp(sizeof(Header));
  hdr->data_size = total - hdr->data_offset;
  hdr->lru_tick = 1;
  hdr->used_bytes = 0;
  hdr->num_objects = 0;
  hdr->num_evicted = 0;
  hdr->num_extents = 1;
  hdr->extents[0] = {hdr->data_offset, hdr->data_size};
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  hdr->magic = kMagic;  // last: attachers spin on magic
  Handle* h = new Handle{hdr, total};
  return h;
}

// Attach to an existing arena (worker processes).
void* ts_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Handle* h = new Handle{hdr, static_cast<uint64_t>(st.st_size)};
  return h;
}

void ts_detach(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  if (!h) return;
  munmap(h->hdr, h->map_size);
  delete h;
}

int ts_destroy(const char* name) { return shm_unlink(name); }

// Allocate space for an object; evicts LRU unpinned sealed objects as
// needed (reaping pins of dead readers before giving up). On success
// writes the data offset to *out_offset and returns the entry index
// (>= 0); negative = error.
int64_t ts_alloc(void* handle, const uint8_t* key, uint64_t size,
                 uint64_t* out_offset) {
  Handle* h = static_cast<Handle*>(handle);
  uint64_t need = AlignUp(size);
  if (need > h->hdr->data_size) return TS_EFULL;
  Locker lock(h->hdr);
  Header* hdr = h->hdr;
  Entry* existing = FindEntry(hdr, key);
  if (existing) return TS_EEXIST;
  Entry* slot = FindSlot(hdr, key);
  if (!slot) return TS_ETABLE;
  int64_t off = AllocFromExtents(hdr, need);
  bool reaped = false;
  while (off < 0) {
    if (EvictOne(hdr) == 0) {
      if (reaped) return TS_EFULL;
      reaped = true;
      if (ReapDeadLocked(hdr) == 0) return TS_EFULL;
      continue;
    }
    off = AllocFromExtents(hdr, need);
  }
  memcpy(slot->key, key, kKeyLen);
  slot->offset = static_cast<uint64_t>(off);
  slot->size = need;
  slot->state = kCreated;
  // Write hold: the producer fills the buffer outside the lock; a
  // concurrent delete must defer the free (zombie) instead of handing
  // the extent to another allocation mid-write.
  slot->pin = 1;
  slot->guard = 0;
  slot->lru = hdr->lru_tick++;
  hdr->used_bytes += need;
  hdr->num_objects++;
  *out_offset = slot->offset;
  return slot - hdr->entries;
}

// Seal the created entry at `idx` (from ts_alloc), releasing the write
// hold; with guard != 0 also takes the owner/primary eviction guard in
// the same critical section. Returns TS_ESTATE if the object was
// deleted mid-write (the entry is then freed here, once the write hold
// drops).
int ts_seal_idx(void* handle, int64_t idx, const uint8_t* key, int guard) {
  Handle* h = static_cast<Handle*>(handle);
  if (idx < 0 || idx >= kEntryCap) return TS_ENOENT;
  Locker lock(h->hdr);
  Entry* e = &h->hdr->entries[idx];
  if (memcmp(e->key, key, kKeyLen) != 0) return TS_ENOENT;
  if (e->state == kZombie) {
    UnpinEntryLocked(h->hdr, e);
    return TS_ESTATE;
  }
  if (e->state != kCreated) return TS_ESTATE;
  e->state = kSealed;
  if (guard) e->guard++;
  if (e->pin > 0) e->pin--;
  return TS_OK;
}

// Look up a sealed object; bumps its LRU stamp.
int ts_lookup(void* handle, const uint8_t* key, uint64_t* out_offset,
              uint64_t* out_size) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e || e->state != kSealed) return TS_ENOENT;
  e->lru = h->hdr->lru_tick++;
  *out_offset = e->offset;
  *out_size = e->size;
  return TS_OK;
}

int ts_contains(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  return (e && e->state == kSealed) ? 1 : 0;
}

// Atomically look up a sealed object and take a read pin on it, so the
// caller's zero-copy view can never alias memory freed by a concurrent
// delete/eviction (lookup-then-pin as two calls would race). Returns
// the entry index (>= 0) for the matching ts_unpin_read; negative =
// error.
int64_t ts_lookup_pin(void* handle, const uint8_t* key,
                      uint64_t* out_offset, uint64_t* out_size) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e || e->state != kSealed) return TS_ENOENT;
  e->lru = h->hdr->lru_tick++;
  e->pin++;
  PinLogAdd(h->hdr, static_cast<uint32_t>(e - h->hdr->entries));
  *out_offset = e->offset;
  *out_size = e->size;
  return e - h->hdr->entries;
}

// Drop the read pin taken by ts_lookup_pin on entry `idx`; frees the
// entry when it was deleted while pinned.
int ts_unpin_read(void* handle, int64_t idx) {
  Handle* h = static_cast<Handle*>(handle);
  if (idx < 0 || idx >= kEntryCap) return TS_ENOENT;
  Locker lock(h->hdr);
  PinLogRemove(h->hdr, static_cast<uint32_t>(idx));
  UnpinEntryLocked(h->hdr, &h->hdr->entries[idx]);
  return TS_OK;
}

// Owner/primary eviction guard (plasma primary-copy pinning analog).
int ts_pin(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  e->guard++;
  return TS_OK;
}

int ts_unpin(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  if (e->guard > 0) e->guard--;
  return TS_OK;
}

// Owner-driven delete: drops the eviction guard and removes the object
// from the table. If readers still hold views (pin > 0) the memory is
// retained as a zombie and freed on the last ts_unpin_read.
int ts_delete(void* handle, const uint8_t* key) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  Entry* e = FindEntry(h->hdr, key);
  if (!e) return TS_ENOENT;
  e->guard = 0;
  if (e->pin > 0) {
    e->state = kZombie;
  } else {
    DeleteEntryLocked(h->hdr, e);
  }
  return TS_OK;
}

uint8_t* ts_base(void* handle) {
  return reinterpret_cast<uint8_t*>(static_cast<Handle*>(handle)->hdr);
}

uint64_t ts_used_bytes(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  return h->hdr->used_bytes;
}

uint64_t ts_num_objects(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  return h->hdr->num_objects;
}

uint64_t ts_num_evicted(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h->hdr);
  return h->hdr->num_evicted;
}

uint64_t ts_capacity(void* handle) {
  return static_cast<Handle*>(handle)->hdr->data_size;
}

}  // extern "C"
